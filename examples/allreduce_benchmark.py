#!/usr/bin/env python
"""Allreduce microbenchmark: bus bandwidth and scaling efficiency.

The driver's north-star metric is allreduce scaling efficiency at 8→256
chips (BASELINE.md). This harness measures, for a sweep of buffer sizes:

- achieved allreduce algorithmic bandwidth (2·N·(size-1)/size bytes moved
  per chip per ring allreduce — the standard bus-bandwidth formula), and
- weak-scaling efficiency = t(1 chip) / t(N chips) for fixed per-chip
  payload (1.0 = perfect).

Runs on whatever mesh is visible: one real chip today, a pod slice
unmodified. On a single chip the collective is a self-reduction, so the
numbers are an upper bound / plumbing check.

Modes:
- default: compiled in-SPMD collective (the hot path).
- ``--engine``: the background-engine path — host numpy buffers through
  enqueue→fuse→stage→collective→host, the reference's CudaOnCPU staging
  shape (torch/mpi_ops_v2.cc:78-110). Scored in bytes/µs, the autotuner's
  objective (reference: parameter_manager.h:34-43).
- ``--engine --tensors K``: K equal tensors submitted together per
  iteration — the tensor-fusion stress (reference: docs/tensor-fusion.md);
  compare HVD_FUSION_THRESHOLD=0 vs default 64 MB.

Run: PYTHONPATH=. python examples/allreduce_benchmark.py --sizes-mb 1 16 64
     PYTHONPATH=. python examples/allreduce_benchmark.py --engine \
         --sizes-kb 1 64 1024 65536 --tensors 16

Multi-process (the engine control plane under negotiation — the
``--decompose`` table then carries the NEGOTIATE phase, split cached vs
full by the response cache; compare against HVD_CACHE_CAPACITY=0 run
sequentially for the measured win, docs/running.md "Negotiation cache"):
     python -m horovod_tpu.run -np 2 --cpu -- python \
         examples/allreduce_benchmark.py --engine --tensors 8 \
         --sizes-kb 64 --iters 30 --decompose --json
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.collectives import (
    HVD_AXIS,
    ranked_allgather,
    ranked_allreduce,
    ranked_reducescatter,
)


def _decompose_timeline(path, n_ops):
    """Phase decomposition of the engine round trip from the engine's
    own timeline (VERDICT r3 #6 — enqueue→cycle→stage→collective→fetch).
    Sums B→E durations per activity over every op in the run (warmup
    included) and reports the per-op average: QUEUE is time on the
    submission queue before a cycle drained it (queue spans of tensors
    submitted together OVERLAP — per-op queue time is what a caller
    experiences, not a wall-clock component), WAIT_FOR_DATA the
    host→device staging leg, ALLREDUCE the eager collective incl. the
    device→host fetch, MEMCPY_* the fusion-buffer pack/unpack.

    Multi-controller runs additionally carry NEGOTIATE_* spans; those
    are split by the ``cached`` arg the engines stamp on the span end —
    the negotiate-phase column comparing response-cache fast rounds vs
    full-table rounds (run once with the default cache and once with
    HVD_CACHE_CAPACITY=0 for the measured win). Returns the data for
    ``--json``."""
    import collections
    import json

    stack = {}
    totals = collections.defaultdict(float)
    spans = collections.defaultdict(list)  # activity -> [duration_s]
    neg_durs = {"cached": [], "full": []}
    for ev in json.load(open(path)):
        if not ev or ev.get("ph") not in ("B", "E"):
            continue
        key = (ev.get("pid"), ev.get("tid"))
        if ev["ph"] == "B":
            stack.setdefault(key, []).append((ev.get("name"), ev["ts"]))
        elif stack.get(key):
            name, ts0 = stack[key].pop()
            dur_s = (ev["ts"] - ts0) / 1e6
            totals[name] += dur_s
            spans[name].append(dur_s)
            if str(name).startswith("NEGOTIATE_"):
                cached = ev.get("args", {}).get("cached")
                if cached is not None:
                    neg_durs["cached" if cached else "full"].append(dur_s)
    accounted = sum(totals.values())
    print(f"# per-op phase decomposition ({n_ops} ops):")
    for name, s in sorted(totals.items(), key=lambda kv: -kv[1]):
        print(f"#   {s / n_ops * 1e3:10.2f} ms/op "
              f"{100 * s / accounted:5.1f}%  {name}")
    negotiate = {}
    for kind, durs in neg_durs.items():
        if durs:
            durs.sort()
            negotiate[kind] = {
                "n": len(durs),
                "median_ms": round(durs[len(durs) // 2] * 1e3, 3),
                "total_ms": round(sum(durs) * 1e3, 2),
            }
    if negotiate:
        import os

        parts = [f"{k} n={v['n']} median={v['median_ms']:.3f} ms"
                 for k, v in sorted(negotiate.items())]
        print(f"#   negotiate rounds (HVD_CACHE_CAPACITY="
              f"{os.environ.get('HVD_CACHE_CAPACITY', 'default')}): "
              + " | ".join(parts))

    # Per-SPAN medians over the canonical engine-path phases, for
    # perfwatch/perf.jsonl trending (QUEUE/NEGOTIATE/MEMCPY/ALLREDUCE/
    # MEMCPY_OUT — MEMCPY folds the submit snapshot and the fusion
    # copy-in together: the copy-in cost a tensor pays on the way to the
    # wire; the zero-copy pool/donation work moves exactly these two).
    def _median(names):
        durs = sorted(d for n in names for d in spans.get(n, ()))
        return round(durs[len(durs) // 2] * 1e3, 4) if durs else None

    phase_medians = {
        "QUEUE": _median(["QUEUE"]),
        "NEGOTIATE": _median([n for n in spans
                              if str(n).startswith("NEGOTIATE_")]),
        "MEMCPY": _median(["MEMCPY", "MEMCPY_IN_FUSION_BUFFER"]),
        "ALLREDUCE": _median(["ALLREDUCE"]),
        "MEMCPY_OUT": _median(["MEMCPY_OUT_FUSION_BUFFER"]),
    }
    parts = [f"{k}={v:.4f}" for k, v in phase_medians.items()
             if v is not None]
    print("#   phase medians (ms/span): " + " ".join(parts))
    return {
        "phases_ms_per_op": {k: round(v / n_ops * 1e3, 4)
                             for k, v in totals.items()},
        "phase_medians": phase_medians,
        "negotiate": negotiate or None,
    }


def _latency_fields(before, decompose=False):
    """Per-op submit→complete latency quantiles over the run, from the
    engine latency histograms (``engine.latency.*`` — the same
    instruments the fleet rollup merges world-wide, so a benchmark
    number is directly comparable to a production ``/fleet`` p99).
    ``before`` is a ``histogram_counts()`` snapshot from the start of
    the run; quantiles are computed on the bucket-count DELTAS so a
    warm registry doesn't pollute the window."""
    from horovod_tpu.core import telemetry as _tele

    out = {}
    for name, h in sorted(_tele.REGISTRY.histogram_counts().items()):
        if not name.startswith("engine.latency."):
            continue
        prev = before.get(name)
        counts = (h["counts"] if prev is None else
                  [c - p for c, p in zip(h["counts"], prev["counts"])])
        if not sum(counts):
            continue
        op = name.rsplit(".", 1)[1]
        q = {}
        for label, frac in (("latency_p50_us", 0.5),
                            ("latency_p99_us", 0.99)):
            v = _tele.quantile_from_buckets(h["bounds"], counts, frac)
            q[label] = None if v is None else round(v * 1e6, 1)
        out[op] = q
    if decompose and out:
        parts = [f"{op} p50={q['latency_p50_us']:g}us "
                 f"p99={q['latency_p99_us']:g}us"
                 for op, q in sorted(out.items())]
        print("#   submit->complete latency: " + " | ".join(parts))
    return out


def _wire_split(compressed_bytes, policy_name):
    """Decompose the MEASURED ``engine.wire_bytes.compressed`` counter
    into (payload_bytes, scale_bytes). Exact regardless of how fusion
    and chunk bucketing sliced the buffers: every scale block ships
    ``block`` one-byte payload elements + one 4-byte f32 scale (int8
    and fp8 payloads are both 1 byte), so the payload:scales ratio is
    block:4 for every chunk uniformly."""
    from horovod_tpu.jax.compression import Compression

    pol = Compression.resolve(policy_name)
    payload = compressed_bytes * pol.block // (pol.block + 4)
    return payload, compressed_bytes - payload


def run_engine(args, tl_path):
    """Engine-path sweep: bytes/µs through the async host engine.
    Tensor names are STABLE across iterations (``bench/{i}`` — the
    per-step-gradient pattern a training loop exhibits), so on a
    multi-process world steady-state negotiation rides the response
    cache's bitvector fast path; compare against HVD_CACHE_CAPACITY=0
    for the measured control-plane win.

    With ``--compression int8|fp8`` the engine wire policy is active and
    ``--decompose`` additionally prints the bytes-on-wire split:
    full-width submitted bytes vs what the mesh collectives actually
    shipped (int8 payload + f32 scales, from the engine.wire_bytes
    telemetry counters both engines feed identically), plus a sha256
    digest of the reduced result — run once with HVD_ENGINE=python and
    once with the default native engine to verify the reductions are
    bit-identical under the same policy."""
    import hashlib

    from horovod_tpu.core import engine as eng
    from horovod_tpu.core import telemetry as _tele

    import os as _os

    e = eng.get_engine()
    kind = type(e).__name__
    lat_before = _tele.REGISTRY.histogram_counts()
    policy = args.compression or "none"
    policy_dcn = args.compression_dcn or "none"
    print(f"# engine path ({kind}), fusion_threshold="
          f"{e.fusion_threshold}, tensors/iter={args.tensors}, "
          f"compression={policy}, compression_dcn={policy_dcn}, "
          f"donate={args.donate}, "
          f"HVD_POOL_MAX_BYTES="
          f"{_os.environ.get('HVD_POOL_MAX_BYTES', 'default')}")
    print(f"# {'size/tensor':>12s} {'total':>10s} {'time':>10s} "
          f"{'bytes/us':>9s} {'host_bw':>9s}")
    rows = []
    for kb in args.sizes_kb:
        # --decompose shuts the engine down after each size to flush its
        # timeline; a fresh singleton picks up cleanly.
        e = eng.get_engine()
        elems = max(1, int(kb * 1024 / 4))
        tensors = [np.ones((elems,), np.float32) for _ in range(args.tensors)]
        total = sum(t.nbytes for t in tensors)

        def one_iter(collect=False, bufs=None):
            # --donate: ownership handoff — the engine references the
            # buffers in place (read-only) instead of snapshotting,
            # the MEMCPY phase the pool already cheapened goes to ~0.
            handles = [
                e.allreduce_async(f"bench/{i}", t, average=False,
                                  donate=args.donate)
                for i, t in enumerate(bufs if bufs is not None else tensors)
            ]
            outs = [e.synchronize(h) for h in handles]
            return outs if collect else None

        wire_before = _tele.REGISTRY.flat_counters()
        for _ in range(args.warmup):
            one_iter()
        t0 = time.perf_counter()
        for _ in range(args.iters):
            one_iter()
        wall = time.perf_counter() - t0
        dt = wall / args.iters
        # One extra (untimed) iteration for the reduction digest — the
        # cross-engine bit-identity check the quantized wire format is
        # pinned by. Fresh buffers: under --donate the timed tensors were
        # handed to the engine, and the digest must stay comparable
        # across engines and modes.
        outs = one_iter(collect=True,
                        bufs=[np.ones((elems,), np.float32)
                              for _ in range(args.tensors)])
        digest = hashlib.sha256(
            b"".join(np.ascontiguousarray(o).tobytes()
                     for o in outs)).hexdigest()
        wire_after = _tele.REGISTRY.flat_counters()
        print(f"  {kb:10.1f}kB {total/1e6:8.2f}MB {dt*1e3:8.3f}ms "
              f"{total/dt/1e6:9.1f} {total/dt/1e9:7.2f}GB/s")
        row = {"size_kb": kb, "total_mb": round(total / 1e6, 3),
               "ms_per_iter": round(dt * 1e3, 4),
               "bytes_per_us": round(total / dt / 1e6, 2),
               "digest": digest}
        niters = args.warmup + args.iters + 1

        def _delta(key):
            return wire_after.get(key, 0) - wire_before.get(key, 0)

        wire = {"submitted": _delta("engine.submitted.bytes"),
                "wire": _delta("engine.wire_bytes"),
                "compressed": _delta("engine.wire_bytes.compressed"),
                "dcn": _delta("engine.wire_bytes.dcn"),
                "ici": _delta("engine.wire_bytes.ici")}
        if policy != "none":
            wire["payload"], wire["scales"] = _wire_split(
                wire["compressed"], policy)
        elif policy_dcn != "none" and wire["dcn"]:
            # Two-phase route: the compressed counter IS the DCN tier.
            wire["payload"], wire["scales"] = _wire_split(
                wire["dcn"], policy_dcn)
        if wire["wire"]:
            wire["ratio"] = round(wire["submitted"] / wire["wire"], 3)
        row["wire_bytes"] = wire
        if args.decompose and wire["wire"]:
            pol = policy if policy != "none" else policy_dcn
            parts = (f"payload={wire['payload']/1e6:.2f}MB "
                     f"scales={wire['scales']/1e6:.3f}MB "
                     if "payload" in wire else "")
            print(f"#   bytes on the wire ({pol}): "
                  f"submitted={wire['submitted']/1e6:.2f}MB "
                  f"shipped={wire['wire']/1e6:.2f}MB {parts}"
                  f"-> {wire.get('ratio', 1.0):.2f}x fewer; "
                  f"digest={digest[:16]}")
            if wire["dcn"] or wire["ici"]:
                # Per-tier split of the hierarchical two-phase route:
                # ICI ships full-width 1/L chunks, DCN only the
                # quantized 1/L shard (+scales) — the cross-tier ratio
                # is the number that scales with host count.
                dcn_ratio = (wire["submitted"] / wire["dcn"]
                             if wire["dcn"] else float("inf"))
                print(f"#   per tier: ici={wire['ici']/1e6:.2f}MB "
                      f"dcn={wire['dcn']/1e6:.3f}MB "
                      f"-> {dcn_ratio:.1f}x fewer bytes cross-tier")
        if tl_path:
            from horovod_tpu.core import engine as _e

            # Flush the timeline for parsing; the next size's fresh
            # engine reopens the path with mode "w" and truncates it.
            _e.shutdown_engine()
            row["decompose"] = _decompose_timeline(
                tl_path, niters * args.tensors)
        rows.append(row)
    return {"mode": "engine", "engine": kind, "tensors": args.tensors,
            "iters": args.iters, "compression": policy,
            "compression_dcn": policy_dcn,
            "donate": args.donate,
            "pool_max_bytes": _os.environ.get("HVD_POOL_MAX_BYTES",
                                              "default"),
            "latency": _latency_fields(lat_before,
                                       decompose=args.decompose),
            "rows": rows}


def run_small(args, tl_path):
    """Small-tensor submit→complete throughput (tensors/s): ``--tensors
    N --bytes B`` — N stable names x B bytes per iteration, submitted
    through ONE batched engine call (``submit_n`` /
    ``hvd_engine_enqueue_n``) by default, or per-tensor with
    ``--per-tensor`` for the baseline this PR's acceptance compares
    against. The metric is what a gradient bucket of hundreds of small
    tensors experiences: per-tensor submit OVERHEAD, not bandwidth.

    Two phases keep the timed window honest: throughput is measured with
    the timeline OFF, then (for ``--json``) a short timeline'd rerun on
    a fresh engine supplies ``phase_medians`` — with batching working,
    QUEUE (not MEMCPY) is the residual phase."""
    import hashlib
    import os as _os

    from horovod_tpu.core import engine as eng

    from horovod_tpu.core import telemetry as _tele

    e = eng.get_engine()
    kind = type(e).__name__
    lat_before = _tele.REGISTRY.histogram_counts()
    n = args.tensors
    elems = max(1, args.bytes // 4)
    names = [f"bench/{i}" for i in range(n)]
    tensors = [np.full((elems,), 1.0, np.float32) for _ in range(n)]
    submit_mode = "per-tensor" if args.per_tensor else "batched"
    print(f"# small-tensor mode ({kind}, {submit_mode}): "
          f"{n} x {args.bytes}B per iteration, stable names")

    def one_iter(engine):
        t_sub0 = time.perf_counter()
        if args.per_tensor:
            handles = [engine.allreduce_async(nm, t, average=False)
                       for nm, t in zip(names, tensors)]
        else:
            handles = engine.submit_n("allreduce", [
                eng.SubmitRequest(nm, t, average=False)
                for nm, t in zip(names, tensors)])
        t_sub = time.perf_counter() - t_sub0
        return [engine.synchronize(h) for h in handles], t_sub

    for _ in range(args.warmup):
        one_iter(e)
    submit_s = 0.0
    t0 = time.perf_counter()
    for _ in range(args.iters):
        submit_s += one_iter(e)[1]
    wall = time.perf_counter() - t0
    per_iter = wall / args.iters
    tps = n / per_iter
    # The submit PLANE alone (handles-in-hand rate): what this PR's
    # batched ABI actually changes — the backend (negotiate + execute +
    # drain) is a floor both submit modes share.
    submit_per_iter = submit_s / args.iters
    submit_tps = n / submit_per_iter if submit_per_iter > 0 else 0.0
    # Untimed extra iteration for the reduction digest — the
    # batch-vs-singles / python-vs-C++ bit-identity check.
    outs = one_iter(e)[0]
    digest = hashlib.sha256(
        b"".join(np.ascontiguousarray(o).tobytes()
                 for o in outs)).hexdigest()
    print(f"#   {tps:12,.0f} tensors/s  "
          f"({per_iter * 1e3:.2f} ms per {n}-tensor iteration)")
    print(f"#   {submit_tps:12,.0f} tensors/s submit-plane  "
          f"({submit_per_iter * 1e3:.2f} ms to handles-in-hand)")
    result = {"mode": "engine-small", "engine": kind,
              "submit": submit_mode, "tensors": n, "bytes": args.bytes,
              "iters": args.iters, "tensors_per_s": round(tps, 1),
              "ms_per_iter": round(per_iter * 1e3, 3),
              "submit_tensors_per_s": round(submit_tps, 1),
              "submit_ms_per_iter": round(submit_per_iter * 1e3, 3),
              "latency": _latency_fields(lat_before,
                                         decompose=args.decompose),
              "digest": digest}
    if tl_path:
        # Timeline'd rerun on a fresh engine (2 iterations: one binds
        # the names, one steady-state) — phase medians only; the timed
        # numbers above never paid for timeline writes.
        _os.environ["HVD_TIMELINE"] = tl_path
        eng.shutdown_engine()
        e2 = eng.get_engine()
        for _ in range(2):
            one_iter(e2)
        eng.shutdown_engine()  # flush for parsing
        _os.environ.pop("HVD_TIMELINE", None)
        result["decompose"] = _decompose_timeline(tl_path, 2 * n)
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 4, 16, 64])
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--engine", action="store_true",
                    help="measure the background-engine (host/async) path "
                         "instead of the compiled in-SPMD path")
    ap.add_argument("--sizes-kb", type=float, nargs="+",
                    default=[1, 16, 64, 256, 1024, 16384, 65536, 262144],
                    help="per-tensor sizes for --engine (kB)")
    ap.add_argument("--tensors", type=int, default=None,
                    help="tensors submitted together per iteration "
                         "(--engine; exercises runtime fusion; default 1, "
                         "or 10000 in --bytes small-tensor mode)")
    ap.add_argument("--bytes", type=int, default=None,
                    help="with --engine: small-tensor mode — --tensors N "
                         "stable names x this many bytes each per "
                         "iteration (default 10000 x 4096), submitted "
                         "through ONE batched engine call; reports "
                         "submit→complete throughput in tensors/s and "
                         "(with --json) phase_medians")
    ap.add_argument("--per-tensor", action="store_true",
                    help="with --bytes: submit per-tensor (loop of "
                         "*_async) instead of batched — the baseline the "
                         "batched-submit speedup is measured against")
    ap.add_argument("--donate", action="store_true",
                    help="with --engine: submit with donate=True — the "
                         "zero-copy ownership handoff that skips the "
                         "submit snapshot entirely (compare the MEMCPY "
                         "phase median against a run without it, and "
                         "against HVD_POOL_MAX_BYTES=0 for the pooled "
                         "vs unpooled copy split)")
    ap.add_argument("--decompose", action="store_true",
                    help="with --engine: print the per-phase share table "
                         "of the round trip (queue / stage / collective "
                         "/ fusion memcpys) from the engine timeline. "
                         "Without --engine: additionally time the "
                         "reduce_scatter and all_gather phases an "
                         "allreduce decomposes into — the collective "
                         "shape of the sharded weight update "
                         "(DistributedOptimizer(sharded_update=True))")
    ap.add_argument("--compression", default=None,
                    choices=["none", "int8", "fp8"],
                    help="engine wire-compression policy (block-scaled "
                         "quantization, jax/quantize.py): sets "
                         "HVD_COMPRESSION for the run; with --decompose "
                         "the per-size output gains the bytes-on-wire "
                         "split (full-width vs int8 payload + f32 "
                         "scales) and a reduction digest for the "
                         "python-vs-C++ engine bit-identity check")
    ap.add_argument("--hierarchical", action="store_true",
                    help="route through reduce-scatter(ICI) -> psum(DCN) "
                         "-> all-gather(ICI) (reference: "
                         "HOROVOD_HIERARCHICAL_ALLREDUCE). Needs a "
                         "two-tier world: multi-process, or "
                         "HVD_TWO_TIER_SHAPE=o,i to split one host.")
    ap.add_argument("--compression-dcn", default=None,
                    choices=["none", "int8", "fp8"],
                    help="per-TIER engine wire policy: quantize ONLY the "
                         "cross-tier (DCN) phase of the hierarchical "
                         "two-phase route — ICI reduces at full width "
                         "(sets HVD_COMPRESSION_DCN; implies "
                         "--hierarchical; needs a two-tier world). With "
                         "--decompose the per-size output gains the "
                         "per-tier byte split from the "
                         "engine.wire_bytes.dcn/.ici counters")
    ap.add_argument("--json", action="store_true",
                    help="additionally print ONE machine-readable JSON "
                         "line with the sweep results (and, with "
                         "--decompose, the per-phase + negotiate "
                         "cached/full split) — the engine-path analogue "
                         "of bench.py's line, for tracking round-trip "
                         "latency across rounds")
    args = ap.parse_args()

    import os

    if args.engine and args.bytes:
        # Small-tensor mode defaults (10k x 4KB) — and the steady state
        # needs every name to fit the control/data-plane working sets:
        # a pre-bound pool slab per name, and a response-cache entry per
        # name (a cache smaller than the working set thrashes — all
        # misses, every round full-table — and the run measures cache
        # churn, not submit cost). Explicit env values still win.
        args.tensors = args.tensors or 10000
        os.environ.setdefault("HVD_POOL_BIND_MAX", str(args.tensors))
        os.environ.setdefault("HVD_CACHE_CAPACITY",
                              str(max(2 * args.tensors, 1024)))
    else:
        args.tensors = args.tensors or 1
    if args.compression_dcn and args.compression_dcn != "none":
        args.hierarchical = True
        os.environ["HVD_COMPRESSION_DCN"] = args.compression_dcn
    if args.hierarchical:
        os.environ["HVD_HIERARCHICAL_ALLREDUCE"] = "1"
    if args.compression and args.compression != "none":
        # Before hvd.init(): multi-controller init eagerly creates the
        # engine, which reads the wire policy at construction.
        os.environ["HVD_COMPRESSION"] = args.compression
    tl_path = None
    small = args.engine and bool(args.bytes)
    if args.engine and (args.decompose or (small and args.json)):
        # Must be in the env BEFORE hvd.init(): multi-controller init
        # eagerly creates the engine (negotiation liveness), and only
        # engine construction reads HVD_TIMELINE. Small-tensor mode
        # instead enables it AFTER the timed window, on a fresh engine
        # (run_small) — timeline writes must not distort tensors/s.
        import tempfile

        tl_path = os.path.join(tempfile.mkdtemp(prefix="hvd_tl_"),
                               "timeline.json")
        if not small:
            os.environ["HVD_TIMELINE"] = tl_path
    hvd.init()
    if args.engine:
        result = (run_small(args, tl_path) if small
                  else run_engine(args, tl_path))
        if args.json:
            import json as _json

            result["nproc"] = hvd.num_processes()
            result["cache_capacity"] = os.environ.get(
                "HVD_CACHE_CAPACITY", "default")
            try:
                from horovod_tpu.core import telemetry as _tele

                flat = _tele.REGISTRY.flat()
                result["negotiation_cache"] = {
                    k.rsplit(".", 1)[1]: v for k, v in flat.items()
                    if k.startswith("engine.negotiation.cache_")}
            except Exception:
                pass
            print(_json.dumps(result))
        return
    if args.compression and args.compression != "none":
        print("# note: --compression measures the ENGINE wire format "
              "(use --engine); the compiled-path policy rides "
              "DistributedOptimizer / bench.py --compression")
    n = hvd.size()
    mesh = hvd.mesh()
    from horovod_tpu.ops.collectives import _hier_allreduce_active

    mode = "hierarchical" if _hier_allreduce_active() else "flat"
    if args.hierarchical and mode == "flat":
        print("# WARNING: --hierarchical requested but the world has no "
              "two-tier mesh; falling back to flat "
              "(set HVD_TWO_TIER_SHAPE or run multi-process)")
    print(f"# world: {n} chip(s), platform="
          f"{jax.devices()[0].platform}, mode={mode}")

    rows = []
    for mb in args.sizes_mb:
        elems = int(mb * 1024 * 1024 / 4)
        # Per-chip payload of `elems` f32, stacked over the mesh.
        x = jax.device_put(
            np.ones((n, elems), np.float32),
            NamedSharding(mesh, P(HVD_AXIS)))
        for _ in range(args.warmup):
            float(np.asarray(ranked_allreduce(x)[0]))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = ranked_allreduce(x)
        # Real device->host fetch of a SLICED scalar: block_until_ready is
        # not an execution barrier on the tunneled axon platform (see
        # bench.py), and fetching the whole buffer would bill a multi-MB
        # host transfer to the collective being measured.
        float(np.asarray(out[0]))
        dt = (time.perf_counter() - t0) / args.iters
        payload = elems * 4
        bus_bytes = 2 * payload * (n - 1) / max(n, 1)
        print(f"size={mb:8.1f} MB/chip  time={dt*1e3:8.3f} ms  "
              f"busbw={bus_bytes/dt/1e9:8.2f} GB/s  "
              f"alg_bw={payload/dt/1e9:8.2f} GB/s")
        rows.append({"size_mb": mb, "ms": round(dt * 1e3, 4),
                     "busbw_gbs": round(bus_bytes / dt / 1e9, 3),
                     "alg_bw_gbs": round(payload / dt / 1e9, 3)})

        if not args.decompose:
            continue
        # Phase decomposition of the same payload into the two halves an
        # allreduce is built from — reduce_scatter (each rank keeps the
        # sum of one 1/n chunk) then all_gather of the chunks. This is
        # the collective shape of the sharded weight update
        # (horovod_tpu/jax/sharded.py), so the engine-vs-in-step
        # comparison covers it directly. rs+ag ≈ allreduce is the
        # expected signature on a ring; a large gap means one phase's
        # schedule is mis-tuned.
        def timed(fn, arg, sync):
            for _ in range(args.warmup):
                sync(fn(arg))
            t0 = time.perf_counter()
            out = None
            for _ in range(args.iters):
                out = fn(arg)
            sync(out)
            return (time.perf_counter() - t0) / args.iters

        # Sliced-scalar fetch: the only reliable barrier on the tunneled
        # platform, without billing a multi-MB host transfer (see above).
        def sync(out):
            return float(np.asarray(out.ravel()[0]))

        t_rs = timed(ranked_reducescatter, x, sync)
        scattered = ranked_reducescatter(x)  # (n, elems/n) per-rank chunks
        t_ag = timed(ranked_allgather, scattered, sync)
        print(f"  phases: reduce_scatter={t_rs*1e3:8.3f} ms  "
              f"all_gather={t_ag*1e3:8.3f} ms  "
              f"rs+ag={(t_rs+t_ag)*1e3:8.3f} ms  "
              f"(allreduce {dt*1e3:8.3f} ms)")
        rows[-1]["phases_ms"] = {
            "reduce_scatter": round(t_rs * 1e3, 4),
            "all_gather": round(t_ag * 1e3, 4)}
    if args.json:
        import json as _json

        print(_json.dumps({"mode": "spmd", "world": n,
                           "collective_mode": mode, "rows": rows}))


if __name__ == "__main__":
    main()
