#!/usr/bin/env python
"""Allreduce microbenchmark: bus bandwidth and scaling efficiency.

The driver's north-star metric is allreduce scaling efficiency at 8→256
chips (BASELINE.md). This harness measures, for a sweep of buffer sizes:

- achieved allreduce algorithmic bandwidth (2·N·(size-1)/size bytes moved
  per chip per ring allreduce — the standard bus-bandwidth formula), and
- weak-scaling efficiency = t(1 chip) / t(N chips) for fixed per-chip
  payload (1.0 = perfect).

Runs on whatever mesh is visible: one real chip today, a pod slice
unmodified. On a single chip the collective is a self-reduction, so the
numbers are an upper bound / plumbing check.

Modes:
- default: compiled in-SPMD collective (the hot path).
- ``--engine``: the background-engine path — host numpy buffers through
  enqueue→fuse→stage→collective→host, the reference's CudaOnCPU staging
  shape (torch/mpi_ops_v2.cc:78-110). Scored in bytes/µs, the autotuner's
  objective (reference: parameter_manager.h:34-43).
- ``--engine --tensors K``: K equal tensors submitted together per
  iteration — the tensor-fusion stress (reference: docs/tensor-fusion.md);
  compare HVD_FUSION_THRESHOLD=0 vs default 64 MB.

Run: PYTHONPATH=. python examples/allreduce_benchmark.py --sizes-mb 1 16 64
     PYTHONPATH=. python examples/allreduce_benchmark.py --engine \
         --sizes-kb 1 64 1024 65536 --tensors 16
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.collectives import (
    HVD_AXIS,
    ranked_allgather,
    ranked_allreduce,
    ranked_reducescatter,
)


def _decompose_timeline(path, n_ops):
    """Phase decomposition of the engine round trip from the engine's
    own timeline (VERDICT r3 #6 — enqueue→cycle→stage→collective→fetch).
    Sums B→E durations per activity over every op in the run (warmup
    included) and reports the per-op average: QUEUE is time on the
    submission queue before a cycle drained it (queue spans of tensors
    submitted together OVERLAP — per-op queue time is what a caller
    experiences, not a wall-clock component), WAIT_FOR_DATA the
    host→device staging leg, ALLREDUCE the eager collective incl. the
    device→host fetch, MEMCPY_* the fusion-buffer pack/unpack."""
    import collections
    import json

    stack = {}
    totals = collections.defaultdict(float)
    for ev in json.load(open(path)):
        if not ev or ev.get("ph") not in ("B", "E"):
            continue
        key = (ev.get("pid"), ev.get("tid"))
        if ev["ph"] == "B":
            stack.setdefault(key, []).append((ev.get("name"), ev["ts"]))
        elif stack.get(key):
            name, ts0 = stack[key].pop()
            totals[name] += (ev["ts"] - ts0) / 1e6
    accounted = sum(totals.values())
    print(f"# per-op phase decomposition ({n_ops} ops):")
    for name, s in sorted(totals.items(), key=lambda kv: -kv[1]):
        print(f"#   {s / n_ops * 1e3:10.2f} ms/op "
              f"{100 * s / accounted:5.1f}%  {name}")


def run_engine(args, tl_path):
    """Engine-path sweep: bytes/µs through the async host engine."""
    from horovod_tpu.core import engine as eng

    e = eng.get_engine()
    kind = type(e).__name__
    print(f"# engine path ({kind}), fusion_threshold="
          f"{e.fusion_threshold}, tensors/iter={args.tensors}")
    print(f"# {'size/tensor':>12s} {'total':>10s} {'time':>10s} "
          f"{'bytes/us':>9s} {'host_bw':>9s}")
    for kb in args.sizes_kb:
        # --decompose shuts the engine down after each size to flush its
        # timeline; a fresh singleton picks up cleanly.
        e = eng.get_engine()
        elems = max(1, int(kb * 1024 / 4))
        tensors = [np.ones((elems,), np.float32) for _ in range(args.tensors)]
        total = sum(t.nbytes for t in tensors)

        def one_iter(it):
            handles = [
                e.allreduce_async(f"bench/{it}/{i}", t, average=False)
                for i, t in enumerate(tensors)
            ]
            for h in handles:
                e.synchronize(h)

        for w in range(args.warmup):
            one_iter(f"w{w}")
        t0 = time.perf_counter()
        for i in range(args.iters):
            one_iter(i)
        wall = time.perf_counter() - t0
        dt = wall / args.iters
        print(f"  {kb:10.1f}kB {total/1e6:8.2f}MB {dt*1e3:8.3f}ms "
              f"{total/dt/1e6:9.1f} {total/dt/1e9:7.2f}GB/s")
        if tl_path:
            from horovod_tpu.core import engine as _e

            # Flush the timeline for parsing; the next size's fresh
            # engine reopens the path with mode "w" and truncates it.
            _e.shutdown_engine()
            _decompose_timeline(tl_path,
                                (args.warmup + args.iters) * args.tensors)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 4, 16, 64])
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--engine", action="store_true",
                    help="measure the background-engine (host/async) path "
                         "instead of the compiled in-SPMD path")
    ap.add_argument("--sizes-kb", type=float, nargs="+",
                    default=[1, 16, 64, 256, 1024, 16384, 65536, 262144],
                    help="per-tensor sizes for --engine (kB)")
    ap.add_argument("--tensors", type=int, default=1,
                    help="tensors submitted together per iteration "
                         "(--engine; exercises runtime fusion)")
    ap.add_argument("--decompose", action="store_true",
                    help="with --engine: print the per-phase share table "
                         "of the round trip (queue / stage / collective "
                         "/ fusion memcpys) from the engine timeline. "
                         "Without --engine: additionally time the "
                         "reduce_scatter and all_gather phases an "
                         "allreduce decomposes into — the collective "
                         "shape of the sharded weight update "
                         "(DistributedOptimizer(sharded_update=True))")
    ap.add_argument("--hierarchical", action="store_true",
                    help="route through reduce-scatter(ICI) -> psum(DCN) "
                         "-> all-gather(ICI) (reference: "
                         "HOROVOD_HIERARCHICAL_ALLREDUCE). Needs a "
                         "two-tier world: multi-process, or "
                         "HVD_TWO_TIER_SHAPE=o,i to split one host.")
    args = ap.parse_args()

    import os

    if args.hierarchical:
        os.environ["HVD_HIERARCHICAL_ALLREDUCE"] = "1"
    tl_path = None
    if args.engine and args.decompose:
        # Must be in the env BEFORE hvd.init(): multi-controller init
        # eagerly creates the engine (negotiation liveness), and only
        # engine construction reads HVD_TIMELINE.
        import tempfile

        tl_path = os.path.join(tempfile.mkdtemp(prefix="hvd_tl_"),
                               "timeline.json")
        os.environ["HVD_TIMELINE"] = tl_path
    hvd.init()
    if args.engine:
        run_engine(args, tl_path)
        return
    n = hvd.size()
    mesh = hvd.mesh()
    from horovod_tpu.ops.collectives import _hier_allreduce_active

    mode = "hierarchical" if _hier_allreduce_active() else "flat"
    if args.hierarchical and mode == "flat":
        print("# WARNING: --hierarchical requested but the world has no "
              "two-tier mesh; falling back to flat "
              "(set HVD_TWO_TIER_SHAPE or run multi-process)")
    print(f"# world: {n} chip(s), platform="
          f"{jax.devices()[0].platform}, mode={mode}")

    for mb in args.sizes_mb:
        elems = int(mb * 1024 * 1024 / 4)
        # Per-chip payload of `elems` f32, stacked over the mesh.
        x = jax.device_put(
            np.ones((n, elems), np.float32),
            NamedSharding(mesh, P(HVD_AXIS)))
        for _ in range(args.warmup):
            float(np.asarray(ranked_allreduce(x)[0]))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = ranked_allreduce(x)
        # Real device->host fetch of a SLICED scalar: block_until_ready is
        # not an execution barrier on the tunneled axon platform (see
        # bench.py), and fetching the whole buffer would bill a multi-MB
        # host transfer to the collective being measured.
        float(np.asarray(out[0]))
        dt = (time.perf_counter() - t0) / args.iters
        payload = elems * 4
        bus_bytes = 2 * payload * (n - 1) / max(n, 1)
        print(f"size={mb:8.1f} MB/chip  time={dt*1e3:8.3f} ms  "
              f"busbw={bus_bytes/dt/1e9:8.2f} GB/s  "
              f"alg_bw={payload/dt/1e9:8.2f} GB/s")

        if not args.decompose:
            continue
        # Phase decomposition of the same payload into the two halves an
        # allreduce is built from — reduce_scatter (each rank keeps the
        # sum of one 1/n chunk) then all_gather of the chunks. This is
        # the collective shape of the sharded weight update
        # (horovod_tpu/jax/sharded.py), so the engine-vs-in-step
        # comparison covers it directly. rs+ag ≈ allreduce is the
        # expected signature on a ring; a large gap means one phase's
        # schedule is mis-tuned.
        def timed(fn, arg, sync):
            for _ in range(args.warmup):
                sync(fn(arg))
            t0 = time.perf_counter()
            out = None
            for _ in range(args.iters):
                out = fn(arg)
            sync(out)
            return (time.perf_counter() - t0) / args.iters

        # Sliced-scalar fetch: the only reliable barrier on the tunneled
        # platform, without billing a multi-MB host transfer (see above).
        def sync(out):
            return float(np.asarray(out.ravel()[0]))

        t_rs = timed(ranked_reducescatter, x, sync)
        scattered = ranked_reducescatter(x)  # (n, elems/n) per-rank chunks
        t_ag = timed(ranked_allgather, scattered, sync)
        print(f"  phases: reduce_scatter={t_rs*1e3:8.3f} ms  "
              f"all_gather={t_ag*1e3:8.3f} ms  "
              f"rs+ag={(t_rs+t_ag)*1e3:8.3f} ms  "
              f"(allreduce {dt*1e3:8.3f} ms)")


if __name__ == "__main__":
    main()
