#!/usr/bin/env python
"""Allreduce microbenchmark: bus bandwidth and scaling efficiency.

The driver's north-star metric is allreduce scaling efficiency at 8→256
chips (BASELINE.md). This harness measures, for a sweep of buffer sizes:

- achieved allreduce algorithmic bandwidth (2·N·(size-1)/size bytes moved
  per chip per ring allreduce — the standard bus-bandwidth formula), and
- weak-scaling efficiency = t(1 chip) / t(N chips) for fixed per-chip
  payload (1.0 = perfect).

Runs on whatever mesh is visible: one real chip today, a pod slice
unmodified. On a single chip the collective is a self-reduction, so the
numbers are an upper bound / plumbing check.

Run: PYTHONPATH=. python examples/allreduce_benchmark.py --sizes-mb 1 16 64
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.collectives import HVD_AXIS, ranked_allreduce


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 4, 16, 64])
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    print(f"# world: {n} chip(s), platform="
          f"{jax.devices()[0].platform}")

    for mb in args.sizes_mb:
        elems = int(mb * 1024 * 1024 / 4)
        # Per-chip payload of `elems` f32, stacked over the mesh.
        x = jax.device_put(
            np.ones((n, elems), np.float32),
            NamedSharding(mesh, P(HVD_AXIS)))
        for _ in range(args.warmup):
            jax.block_until_ready(ranked_allreduce(x))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = ranked_allreduce(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / args.iters
        payload = elems * 4
        bus_bytes = 2 * payload * (n - 1) / max(n, 1)
        print(f"size={mb:8.1f} MB/chip  time={dt*1e3:8.3f} ms  "
              f"busbw={bus_bytes/dt/1e9:8.2f} GB/s  "
              f"alg_bw={payload/dt/1e9:8.2f} GB/s")


if __name__ == "__main__":
    main()
