#!/usr/bin/env python
"""MNIST through the Trainer frontend (reference: examples/keras_mnist.py):
DistributedOptimizer wrapping, broadcast callback, lr scaled by size.

Run: PYTHONPATH=. python examples/keras_mnist.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import optax

import horovod_tpu as hvd
import horovod_tpu.keras as hvd_keras
from horovod_tpu.keras.callbacks import (
    BroadcastGlobalVariablesCallback,
    MetricAverageCallback,
)
from horovod_tpu.models import MnistConvNet

from common import synthetic_mnist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args()

    hvd.init()
    (xtr, ytr), (xte, yte) = synthetic_mnist()

    trainer = hvd_keras.Trainer(
        MnistConvNet(),
        optax.adam(args.lr * hvd.size()),  # reference: keras_mnist.py:41
    )
    hist = trainer.fit(
        xtr, ytr, batch_size=args.batch_size, epochs=args.epochs,
        callbacks=[BroadcastGlobalVariablesCallback(0),
                   MetricAverageCallback()],
        validation_data=(xte, yte), verbose=1)
    if len(hist["loss"]) > 1:
        assert hist["loss"][-1] < hist["loss"][0]
    assert hist["val_loss"][-1] == hist["val_loss"][-1]  # finite, not NaN


if __name__ == "__main__":
    main()
