#!/usr/bin/env python
"""MNIST with the dm-haiku frontend — the same flagship training shape
as examples/jax_mnist.py (reference: examples/tensorflow_mnist.py) on
``hk.transform_with_state``: hvd.init, DistributedOptimizer, startup
broadcast of params AND state, per-replica batch-norm statistics
averaged for evaluation.

Run: PYTHONPATH=. python examples/haiku_mnist.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import haiku as hk
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.haiku as hvd_hk

from common import shard_batch, synthetic_mnist


def forward(x, train: bool):
    x = hk.Conv2D(8, 3, stride=2)(x)
    x = hk.BatchNorm(True, True, 0.9)(x, is_training=train)
    x = jax.nn.relu(x)
    x = hk.Conv2D(16, 3, stride=2)(x)
    x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    return hk.Linear(10)(x)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-chip batch size")
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args()

    hvd.init()
    (xtr, ytr), (xte, yte) = synthetic_mnist()

    net = hk.transform_with_state(forward)
    # LR scaled by world size, the reference's canonical scaling
    # (reference: tensorflow_mnist.py:85 `lr * hvd.size()`).
    opt = hvd_hk.DistributedOptimizer(optax.adam(args.lr * hvd.size()))

    params, state = net.init(jax.random.PRNGKey(0),
                             jnp.asarray(xtr[:8]), True)
    # Startup sync of BOTH trees (haiku keeps BN statistics in `state`).
    params = hvd_hk.broadcast_parameters(params, root_rank=0)
    state = hvd_hk.broadcast_state(state, root_rank=0)
    opt_state = opt.init(params)

    def loss_fn(params, state, x, y):
        logits, new_state = net.apply(params, state, None, x, True)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, new_state

    # Donate params/state/opt_state: all three are rebound to the step's
    # outputs, so XLA updates them in place instead of paying a
    # copy-on-update of every param-sized buffer each step.
    @hvd_hk.jit(in_specs=(P(), P(), P(), P(hvd_hk.HVD_AXIS),
                          P(hvd_hk.HVD_AXIS)),
                out_specs=(P(), P(), P(), P()),
                donate_argnums=(0, 1, 2))
    def train_step(params, state, opt_state, x, y):
        (loss, state), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, x, y)
        updates, opt_state = opt.update(g, opt_state, params)
        return (optax.apply_updates(params, updates), state, opt_state,
                hvd_hk.allreduce(loss))

    mesh = hvd.mesh()

    def shard(a):
        return shard_batch(a, mesh, hvd_hk.HVD_AXIS)

    n_local = args.batch_size * hvd.local_size()
    steps = len(xtr) // n_local
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(steps * n_local)
        for s in range(steps):
            sel = perm[s * n_local:(s + 1) * n_local]
            params, state, opt_state, loss = train_step(
                params, state, opt_state, shard(xtr[sel]),
                shard(ytr[sel]))
        print(f"epoch {epoch}: loss={float(loss):.4f}")

    # Per-replica BN statistics are averaged for a world-agreed eval
    # model (the role the reference's MetricAverageCallback family
    # plays for state that is never allreduced during training).
    state = hvd_hk.average_state(state)
    logits, _ = net.apply(params, state, None, jnp.asarray(xte), False)
    acc = float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(yte)))
    print(f"test accuracy: {acc:.3f}")
    assert float(loss) < 2.0, "training did not reduce the loss"


if __name__ == "__main__":
    main()
