#!/usr/bin/env python
"""Torch synthetic benchmark — img/sec through the async-engine allreduce
path (reference: examples/pytorch_synthetic_benchmark.py). This measures
the *host* engine (enqueue → fuse → XLA collective), the path torch
training uses; compiled-in JAX training is benchmarked by bench.py.

Run: PYTHONPATH=. python examples/pytorch_synthetic_benchmark.py \
         --num-iters 3 --model resnet18
"""

import argparse
import os
import sys
import timeit

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import torch
import torchvision_stub  # noqa: F401  (torchvision is absent; stub below)

import horovod_tpu.torch as hvd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-warmup-batches", type=int, default=2)
    ap.add_argument("--num-batches-per-iter", type=int, default=2)
    ap.add_argument("--num-iters", type=int, default=3)
    ap.add_argument("--fp16-allreduce", action="store_true")
    args = ap.parse_args()

    hvd.init()
    model = torchvision_stub.get_model(args.model)
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, 64, 64)
    target = torch.randint(0, 1000, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for _ in range(args.num_iters):
        t = timeit.timeit(benchmark_step,
                          number=args.num_batches_per_iter)
        img_sec = args.batch_size * args.num_batches_per_iter / t
        img_secs.append(img_sec)
        print(f"Iter: {img_sec:.1f} img/sec per chip")
    print(f"Img/sec per chip: {np.mean(img_secs):.1f} "
          f"+-{1.96 * np.std(img_secs):.1f} "
          f"(total over {hvd.size()} ranks: "
          f"{hvd.size() * np.mean(img_secs):.1f})")


if __name__ == "__main__":
    main()
