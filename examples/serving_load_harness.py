#!/usr/bin/env python
"""Serving-plane load harness: mixed-priority traffic under injected chaos.

The overload acceptance gate (ISSUE 20): drive concurrent mixed-priority
requests through the engine — single submits and fused batches together —
while faultline injects exec stalls / KV delays, and prove that overload
is a governed regime, not a failure mode:

- high-class p99 latency stays bounded by its deadline knob,
- admission rejections land on the LOW class only (budgets are per class),
- zero torn fused batches (admission is all-or-nothing per batch),
- zero poisonings: every non-shed completion digest-verifies against the
  exact expected reduction (integer-valued payloads, ``average=False``).

Every rank derives the SAME request schedule from ``--seed`` (names,
classes, deadlines — collectives are symmetric; a mixed-priority world
for one tensor fails fast by name), and each rank contributes a payload
that depends on its rank, so the expected sum is known in closed form.

Run (single process, quick smoke):
    PYTHONPATH=. python examples/serving_load_harness.py --requests 40

The 2-process acceptance shape (small low-class budget to force
rejections, exec stalls + KV delays on rank 0):
    python -m horovod_tpu.run -np 2 --cpu -- python \
        examples/serving_load_harness.py --requests 120 \
        --max-inflight-low 2 --deadline-high-ms 8000 \
        --faults engine.exec:stall:3:0.1,kv.get:delay:5:0.02 \
        --assert-acceptance

Prints exactly one JSON line per rank (per-class latency quantiles,
shed/rejected/timeout tallies, digest failures, admission counters).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _percentile(values, q):
    if not values:
        return None
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=120,
                    help="total single-submit requests (fused batches ride "
                         "on top, one per wave)")
    ap.add_argument("--wave", type=int, default=12,
                    help="max requests in flight at once")
    ap.add_argument("--size", type=int, default=512,
                    help="elements per tensor")
    ap.add_argument("--batch-tensors", type=int, default=4,
                    help="tensors per fused batch (one batch per wave; "
                         "0 disables)")
    ap.add_argument("--deadline-high-ms", type=float, default=8000.0,
                    help="high-class deadline — the acceptance knob")
    ap.add_argument("--deadline-low-ms", type=float, default=4000.0,
                    help="normal/low-class deadline (bounds recovery when "
                         "admission diverges across ranks under chaos)")
    ap.add_argument("--seed", type=int, default=20)
    ap.add_argument("--max-inflight-low", type=int, default=0,
                    help="set HVD_ADMISSION_MAX_INFLIGHT_LOW before the "
                         "engine starts (0 = leave env alone)")
    ap.add_argument("--faults", default="",
                    help="HVD_FAULTS spec to arm in THIS process (the "
                         "launcher's --faults RANK:SPEC scopes per rank)")
    ap.add_argument("--assert-acceptance", action="store_true",
                    help="exit nonzero unless the ISSUE-20 gate holds: "
                         "high p99 <= deadline knob, rejections on low "
                         "only (and present), zero torn batches, zero "
                         "digest failures")
    args = ap.parse_args()

    # Knobs must land in the env BEFORE the engine singleton is built
    # (config_from_env reads them once, at construction).
    if args.max_inflight_low > 0:
        os.environ["HVD_ADMISSION_MAX_INFLIGHT_LOW"] = str(
            args.max_inflight_low)
    if args.faults:
        os.environ["HVD_FAULTS"] = args.faults

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.core import telemetry as tele
    from horovod_tpu.core.engine import (
        AdmissionRejected,
        CollectiveTimeout,
        admission_summary,
        get_engine,
    )
    from horovod_tpu.jax import mpi_ops

    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    pid, nproc = hvd.process_index(), hvd.num_processes()
    local = hvd.local_size()
    eng = get_engine()

    rng = np.random.RandomState(args.seed)  # IDENTICAL on every rank
    classes = rng.choice(["high", "normal", "low"], size=args.requests,
                         p=[0.25, 0.30, 0.45])
    deadline_ms = {"high": args.deadline_high_ms,
                   "normal": args.deadline_low_ms,
                   "low": args.deadline_low_ms}
    # Engine allreduce semantics: the staged host buffer rides the chip
    # mesh, so process q's payload is counted local_size(q) times. With
    # payload i on process p = (i % 11 + 1) * (p + 1) and equal local
    # sizes, the exact sum is (i % 11 + 1) * local * nproc*(nproc+1)/2.
    rank_sum = local * nproc * (nproc + 1) / 2.0

    stats = {c: dict(submitted=0, completed=0, rejected=0, timeout=0,
                     failed=0, latencies_ms=[]) for c in
             ("high", "normal", "low")}
    digest_failures = 0
    torn_batches = 0
    outstanding = {}  # handle -> (name, cls, t0, expected, batch_id)
    batch_state = {}  # batch_id -> dict(done=0, bad=0, n=N)

    def drain(budget):
        """Poll outstanding handles; resolve completions/errors."""
        nonlocal digest_failures, torn_batches
        resolved = 0
        for h in list(outstanding):
            if not eng.poll(h):
                continue
            name, cls, t0, expected, batch_id = outstanding.pop(h)
            lat_ms = (time.monotonic() - t0) * 1e3
            try:
                out = mpi_ops.synchronize(h)
            except CollectiveTimeout:
                stats[cls]["timeout"] += 1
                if batch_id is not None:
                    batch_state[batch_id]["bad"] += 1
            except Exception:
                stats[cls]["failed"] += 1
                if batch_id is not None:
                    batch_state[batch_id]["bad"] += 1
            else:
                stats[cls]["completed"] += 1
                stats[cls]["latencies_ms"].append(lat_ms)
                if not np.allclose(np.asarray(out), expected):
                    digest_failures += 1
                if batch_id is not None:
                    batch_state[batch_id]["done"] += 1
            resolved += 1
            if resolved >= budget:
                break
        return resolved

    next_batch = 0
    for i in range(args.requests):
        cls = str(classes[i])
        value = float(i % 11 + 1)
        payload = np.full(args.size, value * (pid + 1), dtype=np.float32)
        expected = value * rank_sum
        # Back-pressure: keep at most --wave requests in flight. This is
        # the continuous-admission shape — the queue stays loaded, so the
        # per-class budgets actually bite.
        while len(outstanding) >= args.wave:
            if drain(args.wave) == 0:
                time.sleep(0.002)
        t0 = time.monotonic()
        try:
            h = mpi_ops.allreduce_async(
                payload, average=False, name=f"serve.{cls}.{i}",
                priority=cls, deadline_ms=deadline_ms[cls])
        except AdmissionRejected:
            stats[cls]["rejected"] += 1
        else:
            stats[cls]["submitted"] += 1
            outstanding[h] = (f"serve.{cls}.{i}", cls, t0, expected, None)
        # One fused batch per wave: uniform class (fusion is
        # priority-uniform), admission is all-or-nothing for the batch.
        if (args.batch_tensors and i % args.wave == args.wave - 1):
            bid = next_batch
            next_batch += 1
            bvals = [float((bid + k) % 7 + 1)
                     for k in range(args.batch_tensors)]
            tensors = [np.full(args.size, v * (pid + 1), dtype=np.float32)
                       for v in bvals]
            names = [f"serve.batch.{bid}.{k}"
                     for k in range(args.batch_tensors)]
            t0 = time.monotonic()
            try:
                hs = mpi_ops.allreduce_n_async(
                    tensors, average=False, names=names, priority="normal",
                    deadline_ms=args.deadline_low_ms)
            except AdmissionRejected:
                stats["normal"]["rejected"] += args.batch_tensors
            else:
                batch_state[bid] = dict(done=0, bad=0,
                                        n=args.batch_tensors)
                for k, h in enumerate(hs):
                    stats["normal"]["submitted"] += 1
                    outstanding[h] = (names[k], "normal", t0,
                                      bvals[k] * rank_sum, bid)

    while outstanding:
        if drain(len(outstanding)) == 0:
            time.sleep(0.002)

    # A torn batch = some members completed while others failed. The
    # admission contract makes submit-time tearing impossible; mid-flight
    # the members share one deadline, so they resolve together.
    for st in batch_state.values():
        if st["done"] and st["done"] + st["bad"] == st["n"] and st["bad"]:
            torn_batches += 1

    flat = tele.REGISTRY.flat_counters()  # syncs the native fold too
    report = {
        "rank": rank, "world": world, "engine": type(eng).__name__,
        "classes": {
            c: dict(submitted=st["submitted"], completed=st["completed"],
                    rejected=st["rejected"], timeout=st["timeout"],
                    failed=st["failed"],
                    p50_ms=_percentile(st["latencies_ms"], 0.50),
                    p99_ms=_percentile(st["latencies_ms"], 0.99))
            for c, st in stats.items()},
        "digest_failures": digest_failures,
        "torn_batches": torn_batches,
        "counters": {
            "engine.admission.rejected":
                int(flat.get("engine.admission.rejected", 0)),
            "engine.admission.shed":
                int(flat.get("engine.admission.shed", 0)),
            "numerics.nonfinite.steps":
                int(flat.get("numerics.nonfinite.steps", 0)),
        },
        "admission": admission_summary(),
    }
    print(json.dumps(report), flush=True)

    ok = True
    if args.assert_acceptance:
        high_p99 = report["classes"]["high"]["p99_ms"]
        ok = (digest_failures == 0 and torn_batches == 0
              and report["counters"]["numerics.nonfinite.steps"] == 0
              and stats["high"]["rejected"] == 0
              and stats["normal"]["rejected"] == 0
              and stats["low"]["rejected"] > 0
              and high_p99 is not None
              and high_p99 <= args.deadline_high_ms)
        if not ok:
            print(f"[rank {rank}] acceptance FAILED: high_p99={high_p99} "
                  f"rejected={[stats[c]['rejected'] for c in stats]} "
                  f"digest={digest_failures} torn={torn_batches}",
                  file=sys.stderr, flush=True)
    elif digest_failures or torn_batches:
        ok = False

    hvd.shutdown()
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
