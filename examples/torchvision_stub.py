"""Minimal torch model zoo for the torch benchmark example (torchvision is
not in this image; the reference pulls models from it —
examples/pytorch_synthetic_benchmark.py:34)."""

import torch.nn as nn


def _block(cin, cout, stride=1):
    return nn.Sequential(
        nn.Conv2d(cin, cout, 3, stride=stride, padding=1, bias=False),
        nn.BatchNorm2d(cout), nn.ReLU(inplace=True),
        nn.Conv2d(cout, cout, 3, padding=1, bias=False),
        nn.BatchNorm2d(cout), nn.ReLU(inplace=True))


class SmallResNet(nn.Module):
    def __init__(self, widths=(64, 128, 256, 512), num_classes=1000):
        super().__init__()
        layers = [nn.Conv2d(3, widths[0], 7, stride=2, padding=3,
                            bias=False),
                  nn.BatchNorm2d(widths[0]), nn.ReLU(inplace=True),
                  nn.MaxPool2d(3, stride=2, padding=1)]
        cin = widths[0]
        for w in widths:
            layers.append(_block(cin, w, stride=1 if w == cin else 2))
            cin = w
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        x = self.pool(self.features(x)).flatten(1)
        return self.fc(x)


def get_model(name: str) -> nn.Module:
    if name in ("resnet18", "resnet34", "resnet50"):
        return SmallResNet()
    raise ValueError(f"unknown model {name}")
