#!/usr/bin/env python
"""MNIST with the PyTorch frontend (reference: examples/pytorch_mnist.py):
hvd.DistributedOptimizer hooks, broadcast of parameters and optimizer
state. Torch computes on CPU; collectives ride the XLA engine.

Run: PYTHONPATH=. python examples/pytorch_mnist.py --epochs 1
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd

from common import synthetic_mnist


class Net(nn.Module):
    """The reference example's model (pytorch_mnist.py:23-39)."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.conv2_drop = nn.Dropout2d()
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2_drop(self.conv2(x)), 2))
        x = x.reshape(-1, 320)
        x = F.relu(self.fc1(x))
        x = F.dropout(x, training=self.training)
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--momentum", type=float, default=0.5)
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(42 + hvd.rank())
    (xtr, ytr), _ = synthetic_mnist()
    xtr = torch.from_numpy(np.transpose(xtr, (0, 3, 1, 2)))
    ytr = torch.from_numpy(ytr.astype(np.int64))

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd.size(),
                                momentum=args.momentum)
    # Reference integration (pytorch_mnist.py:102-110): broadcast state,
    # wrap the optimizer.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    model.train()
    first = last = None
    for epoch in range(args.epochs):
        for i in range(0, len(xtr) - args.batch_size, args.batch_size):
            data = xtr[i:i + args.batch_size]
            target = ytr[i:i + args.batch_size]
            optimizer.zero_grad()
            loss = F.nll_loss(model(data), target)
            loss.backward()
            optimizer.step()
            if first is None:
                first = loss.item()
            last = loss.item()
        print(f"epoch {epoch}: loss={last:.4f}")
    assert last < first, (first, last)


if __name__ == "__main__":
    main()
