#!/usr/bin/env python
"""Scaling-efficiency harness — the reference's headline claim, measured.

The reference's banner numbers are scaling efficiencies (90% for
ResNet-101/Inception V3, 68% for VGG-16 at 512 GPUs — reference:
docs/benchmarks.md:1-7); BASELINE.json's north star is >=85% allreduce
scaling 8->256 v5e chips. This script produces those two curves on
whatever world it is started in:

  PYTHONPATH=. python examples/scaling_benchmark.py            # full sweep
  PYTHONPATH=. python examples/scaling_benchmark.py --chips 1 4 8
  PYTHONPATH=. python examples/scaling_benchmark.py --model resnet50

For each chip count n (powers of two up to the world, by default) it
re-forms the world from the first n chips (``hvd.init(ranks=...)`` — the
reference's ``init(comm=...)`` subset form) and measures:

- **allreduce bus bandwidth**: ring-equivalent ``2*(n-1)/n * bytes / t``
  for each ``--sizes-mb``, the metric NCCL tests report — how close the
  collective rides the ICI links.
- **end-to-end scaling efficiency** (with ``--model``): synthetic
  training images/sec at n chips vs n * (images/sec at 1 chip) — the
  reference's definition.

On this CI rig only one real chip exists; the sweep then degenerates to
n=1 (still useful as the per-chip baseline). The multi-chip mechanics —
subset meshes, re-init, per-n compiled programs — are exercised on the
8-device virtual CPU mesh in tests/test_examples_smoke.py, so the
harness is known-good when real multi-chip hardware shows up.
"""

import argparse
import time

import numpy as np


def _timeit(fn, barrier, warmup=2, iters=8):
    """Timed window ending in ``barrier(out)`` — a real device->host
    fetch, because ``block_until_ready`` is not an execution barrier on
    the tunneled platform (see bench.py). The one timing convention for
    both the allreduce and training measurements in this file."""
    for _ in range(warmup):
        out = fn()
    barrier(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    barrier(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--chips", type=int, nargs="+", default=None,
                    help="chip counts to sweep (default: powers of 2 up "
                         "to the full world)")
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1.0, 16.0, 64.0])
    ap.add_argument("--model", default=None,
                    help="also measure end-to-end training scaling "
                         "efficiency for this model (e.g. resnet50)")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--hierarchical-sweep", action="store_true",
                    help="instead of the chip-count sweep: on the full "
                         "world, trend flat vs hierarchical vs "
                         "hierarchical+int8-DCN allreduce per size — the "
                         "two-tier route's cross-tier byte win, measured "
                         "(simulates a multi-host mesh via "
                         "--two-tier-shape on one host)")
    ap.add_argument("--two-tier-shape", default=None,
                    help="o,i (dcn,ici) split for --hierarchical-sweep "
                         "(default: 2,<world/2> — two simulated hosts)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    world = hvd.size()
    hvd.shutdown()
    if args.hierarchical_sweep:
        return _hier_sweep(args, world)
    chips = args.chips or [n for n in (2 ** i for i in range(20))
                           if n <= world]
    skipped = [n for n in chips if n > world]
    if skipped:
        print(f"# skipping {skipped}: world has only {world} chip(s)")
        chips = [n for n in chips if n <= world]
    if not chips:
        raise SystemExit(f"no requested chip count fits the {world}-chip "
                         "world; nothing to sweep")

    e2e_base = None  # per-chip throughput at the SMALLEST swept n
    print(f"# world: {world} chip(s); sweeping {chips}")
    print("chips | " + " | ".join(f"allreduce {s:g}MB GB/s(bus)"
                                  for s in args.sizes_mb)
          + (f" | img/s | efficiency vs n={chips[0]}" if args.model
             else ""))
    for n in chips:
        hvd.init(ranks=list(range(n)))
        assert hvd.size() == n
        row = [f"{n:5d}"]
        for size_mb in args.sizes_mb:
            if n == 1:
                row.append("     n/a")  # no wire to measure
                continue
            # Compiled in-SPMD allreduce (allreduce_benchmark.py's
            # default mode): the eager path would re-stage the buffer
            # host->device inside the timed window and bill staging, not
            # the ICI collective, to the scaling number.
            from jax.sharding import NamedSharding, PartitionSpec

            from horovod_tpu.ops.collectives import ranked_allreduce

            elems = int(size_mb * 1024 * 1024 / 4)
            x = jax.device_put(
                jnp.ones((n, elems), jnp.float32),
                NamedSharding(hvd.mesh(), PartitionSpec("hvd")))
            fn = lambda: ranked_allreduce(x)  # noqa: E731
            # Sliced-scalar fetch: a whole-buffer fetch would bill a
            # multi-MB host transfer to the collective.
            t = _timeit(fn, lambda o: float(np.asarray(o[0])))
            bus = (2 * (n - 1) / n) * elems * 4 / t / 1e9
            row.append(f"{bus:8.2f}")
        if args.model:
            img_s = _train_throughput(args, n)
            # The reference defines efficiency against the 1-chip rate;
            # when a --chips list omits 1, the smallest swept n stands in
            # (and the column header says so).
            eff = (img_s / (n * e2e_base)) if e2e_base else 1.0
            if e2e_base is None:
                e2e_base = img_s / n
            row.append(f"{img_s:8.1f}")
            row.append(f"{100 * eff:5.1f}%")
        print(" | ".join(row), flush=True)
        hvd.shutdown()


def _hier_sweep(args, world):
    """Flat vs hierarchical vs hierarchical+int8-DCN allreduce on the
    full world: the two-tier composition's trend line. On one host the
    (dcn, ici) split is SIMULATED (HVD_TWO_TIER_SHAPE), so the timing
    columns share one interconnect — the structural number to watch is
    the cross-tier byte column: int8-DCN ships bytes/(L*~4) across the
    slow tier, the term that dominates once 'dcn' is a real network."""
    import os

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    import horovod_tpu as hvd
    from horovod_tpu.ops.collectives import ranked_allreduce

    if world < 4:
        raise SystemExit(f"--hierarchical-sweep needs >=4 chips to "
                         f"split into two tiers; world has {world}")
    shape = args.two_tier_shape or f"2,{world // 2}"
    outer, inner = (int(v) for v in shape.split(","))
    modes = (("flat", {}, "none"),
             ("hier", {"HVD_TWO_TIER_SHAPE": shape,
                       "HVD_HIERARCHICAL_ALLREDUCE": "1"}, "none"),
             ("hier+int8dcn", {"HVD_TWO_TIER_SHAPE": shape,
                               "HVD_HIERARCHICAL_ALLREDUCE": "1"}, "int8"))
    print(f"# world: {world} chip(s); two-tier shape dcn={outer} x "
          f"ici={inner} (simulated on one host)")
    print(f"# {'size':>8s} | " + " | ".join(f"{m:>14s} ms" for m, _, _
                                            in modes)
          + " | cross-tier bytes flat vs int8-dcn")
    for size_mb in args.sizes_mb:
        elems = int(size_mb * 1024 * 1024 / 4)
        times = []
        for _, env, dcn_wire in modes:
            for k, v in env.items():
                os.environ[k] = v
            hvd.init()
            try:
                x = jax.device_put(
                    jnp.ones((world, elems), jnp.float32),
                    NamedSharding(hvd.mesh(), PartitionSpec("hvd")))
                fn = lambda: ranked_allreduce(x, dcn_wire=dcn_wire)  # noqa: E731
                times.append(_timeit(
                    fn, lambda o: float(np.asarray(o[0]))))
            finally:
                hvd.shutdown()
                for k in env:
                    os.environ.pop(k, None)
        # Cross-tier byte model (per chip, one allreduce): flat ships
        # the full ring volume across every hop; the two-phase route
        # ships only the quantized 1/L shard (+ f32 scales per 512
        # block) across the slow tier.
        from horovod_tpu.jax import quantize as Q
        from horovod_tpu.jax.compression import Compression

        pol = Compression.int8
        flat_bytes = elems * 4
        n_ici = Q.padded_len(elems, inner) // inner
        npad = Q.padded_len(n_ici, outer * pol.block)
        dcn_bytes = npad + (npad // pol.block) * 4  # i8 payload + scales
        print(f"# {size_mb:6.1f}MB | "
              + " | ".join(f"{t * 1e3:14.3f}   " for t in times)
              + f" | {flat_bytes / 1e6:.2f}MB vs {dcn_bytes / 1e6:.3f}MB "
                f"({flat_bytes / dcn_bytes:.1f}x fewer)", flush=True)


def _train_throughput(args, n):
    """Synthetic training images/sec on the current n-chip world
    (bench.py's methodology at sweep-friendly step counts)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax
    from horovod_tpu import models

    model = models.get_model(args.model)
    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    x = np.random.uniform(size=(args.batch_size, args.image_size,
                                args.image_size, 3)).astype(jnp.bfloat16)
    y = np.random.randint(0, model.num_classes, size=(args.batch_size,))
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x), False)
    params, bstats = variables["params"], variables.get("batch_stats", {})
    opt_state = opt.init(params)

    def loss_fn(p, bs, xx, yy, dk):
        # Dropout models (vgg16/inceptionv3) need an rng; others ignore it
        # (bench.py threads the same stream).
        logits, mut = model.apply({"params": p, "batch_stats": bs}, xx,
                                  True, mutable=["batch_stats"],
                                  rngs={"dropout": dk})
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yy).mean(), mut["batch_stats"]

    @hvd_jax.jit(in_specs=(P(), P(), P(), P(), P(hvd_jax.HVD_AXIS),
                           P(hvd_jax.HVD_AXIS)),
                 out_specs=(P(), P(), P(), P(), P()),
                 donate_argnums=(0, 1, 2))
    def step(p, bs, s, key, xx, yy):
        key, dk = jax.random.split(key)
        (loss, bs), g = jax.value_and_grad(loss_fn, has_aux=True)(
            p, bs, xx, yy, dk)
        up, s = opt.update(g, s, p)
        return (optax.apply_updates(p, up), bs, s, key,
                hvd_jax.allreduce(loss))

    mesh = hvd.mesh()
    from jax.sharding import NamedSharding

    def shard(a):
        shards = [jax.device_put(a, d) for d in jax.local_devices()
                  if d in mesh.devices.flat]
        return jax.make_array_from_single_device_arrays(
            (a.shape[0] * hvd.size(),) + a.shape[1:],
            NamedSharding(mesh, P(hvd_jax.HVD_AXIS)), shards)

    xx, yy = shard(x), shard(np.asarray(y))

    key = jax.random.PRNGKey(0)

    def run():
        nonlocal params, bstats, opt_state, key
        for _ in range(args.steps):
            params, bstats, opt_state, key, loss = step(
                params, bstats, opt_state, key, xx, yy)
        return loss

    dt = _timeit(run, lambda loss: float(np.asarray(loss)),
                 warmup=1, iters=1)
    return args.batch_size * hvd.size() * args.steps / dt


if __name__ == "__main__":
    main()
