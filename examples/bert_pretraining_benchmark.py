#!/usr/bin/env python
"""BERT-base pretraining benchmark — the tensor-fusion stress config of
BASELINE.json (many large gradient buckets). Measures tokens/sec/chip for
the compiled data-parallel training step with fused per-dtype gradient
allreduce.

Run: PYTHONPATH=. python examples/bert_pretraining_benchmark.py \
         --layers 2 --hidden 128 --seq-len 128 --steps 4
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
from horovod_tpu.models import TransformerConfig, TransformerLM


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--vocab", type=int, default=30522)
    ap.add_argument("--batch-size", type=int, default=8,
                    help="per-chip batch")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--remat", action="store_true",
                    help="checkpoint each layer (HBM for FLOPs)")
    ap.add_argument("--flash", action="store_true",
                    help="use the pallas flash-attention kernel "
                         "(forward + backward) instead of stock attention")
    args = ap.parse_args()

    hvd.init()
    attention_fn = None
    if args.flash:
        from horovod_tpu.ops.flash_attention import flash_attention

        attention_fn = flash_attention  # BERT is bidirectional
    cfg = TransformerConfig(
        vocab_size=args.vocab, num_layers=args.layers,
        num_heads=args.heads, hidden_dim=args.hidden,
        mlp_dim=4 * args.hidden, max_len=args.seq_len,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        remat=args.remat, attention_fn=attention_fn)
    model = TransformerLM(cfg)
    opt = hvd_jax.DistributedOptimizer(
        optax.adamw(1e-4, weight_decay=0.01))

    rng = np.random.RandomState(0)
    tokens = rng.randint(
        0, args.vocab,
        size=(args.batch_size * hvd.local_size(), args.seq_len)
    ).astype(np.int32)

    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens[:1]))
    params = hvd_jax.broadcast_parameters(variables["params"])
    opt_state = opt.init(params)
    n_params = sum(int(np.prod(p.shape)) for p in
                   jax.tree_util.tree_leaves(params))
    print(f"# params: {n_params/1e6:.1f}M, {hvd.size()} chip(s)")

    def loss_fn(params, toks):
        logits = model.apply({"params": params}, toks)
        tgt = jnp.roll(toks, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    @hvd_jax.jit(in_specs=(P(), P(), P(hvd_jax.HVD_AXIS)),
                 out_specs=(P(), P(), P()), donate_argnums=(0, 1))
    def step(params, opt_state, toks):
        loss, g = jax.value_and_grad(loss_fn)(params, toks)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            hvd_jax.allreduce(loss)

    toks = jnp.asarray(tokens)
    for _ in range(args.warmup):
        params, opt_state, loss = step(params, opt_state, toks)
    # Real device->host fetch: block_until_ready is not an execution
    # barrier on the tunneled axon platform (see bench.py).
    float(np.asarray(loss))

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, toks)
    float(np.asarray(loss))
    dt = time.perf_counter() - t0
    tok_per_sec = args.batch_size * args.seq_len * args.steps / dt
    print(f"tokens/sec/chip: {tok_per_sec:.0f}  loss={float(loss):.3f}")


if __name__ == "__main__":
    main()
