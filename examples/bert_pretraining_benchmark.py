#!/usr/bin/env python
"""BERT-base pretraining benchmark — the tensor-fusion stress config of
BASELINE.json (many large gradient buckets). Measures tokens/sec/chip for
the compiled data-parallel training step with fused per-dtype gradient
allreduce.

Run: PYTHONPATH=. python examples/bert_pretraining_benchmark.py \
         --layers 2 --hidden 128 --seq-len 128 --steps 4
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
from horovod_tpu.models import TransformerConfig, TransformerLM


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--vocab", type=int, default=30522)
    ap.add_argument("--batch-size", type=int, default=8,
                    help="per-chip batch")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--steps-per-call", type=int, default=5,
                    help="steps fused into one dispatch via lax.scan "
                         "(amortizes per-call host latency; see bench.py)")
    ap.add_argument("--unroll", type=int, default=5,
                    help="scan unroll factor: lets XLA software-pipeline "
                         "across step boundaries (bench.py --unroll; "
                         "measured +3.8%% tokens/sec on BERT-base here)")
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--remat", action="store_true",
                    help="checkpoint each layer (HBM for FLOPs)")
    ap.add_argument("--flash", action="store_true",
                    help="use the pallas flash-attention kernel "
                         "(forward + backward) instead of stock attention")
    ap.add_argument("--dropout", action="store_true",
                    help="train with the model's dropout active (0.1): "
                         "the pretraining-realistic configuration; "
                         "default off isolates compute throughput")
    ap.add_argument("--fused-loss", action="store_true",
                    help="chunked LM-head cross-entropy: never "
                         "materializes the [tokens, vocab] logits "
                         "(ops/chunked_loss.py)")
    ap.add_argument("--loss-chunk", type=int, default=1024,
                    help="vocab tile width for --fused-loss (1024 is the "
                         "largest that fits the 16 MB scoped-VMEM stack)")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture an XLA profiler trace of one timed "
                         "window (summarize: python -m "
                         "horovod_tpu.utils.xplane DIR)")
    args = ap.parse_args()

    if args.dropout and "JAX_DEFAULT_PRNG_IMPL" not in os.environ:
        # Counter-based rbg keys: threefry key derivation/mask generation
        # costs ~17% of the BERT-base step (measured, docs/benchmarks.md);
        # rbg brings active dropout to ~5%. Env var overrides.
        jax.config.update("jax_default_prng_impl", "rbg")

    hvd.init()
    attention_fn = None
    if args.flash:
        from horovod_tpu.ops.flash_attention import flash_attention

        attention_fn = flash_attention  # BERT is bidirectional
    cfg = TransformerConfig(
        vocab_size=args.vocab, num_layers=args.layers,
        num_heads=args.heads, hidden_dim=args.hidden,
        mlp_dim=4 * args.hidden, max_len=args.seq_len,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        remat=args.remat, attention_fn=attention_fn)
    model = TransformerLM(cfg)
    # fused_update: tiny layernorm/bias tensors update through per-dtype
    # buffers (horovod_tpu/jax/fused.py) — adamw is elementwise.
    opt = hvd_jax.DistributedOptimizer(
        optax.adamw(1e-4, weight_decay=0.01), fused_update=True)

    rng = np.random.RandomState(0)
    tokens = rng.randint(
        0, args.vocab,
        size=(args.batch_size * hvd.local_size(), args.seq_len)
    ).astype(np.int32)

    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens[:1]))
    params = hvd_jax.broadcast_parameters(variables["params"])
    opt_state = opt.init(params)
    n_params = sum(int(np.prod(p.shape)) for p in
                   jax.tree_util.tree_leaves(params))
    print(f"# params: {n_params/1e6:.1f}M, {hvd.size()} chip(s)")

    # deterministic=False + a per-step rng = the pretraining-realistic
    # dropout configuration (--dropout); the default isolates compute.
    det = not args.dropout

    def _apply(params, toks, dk, **kw):
        rngs = {"dropout": dk} if args.dropout else None
        return model.apply({"params": params}, toks, deterministic=det,
                           rngs=rngs, **kw)

    if args.fused_loss:
        from horovod_tpu.ops.chunked_loss import fused_softmax_cross_entropy

        def loss_fn(params, toks, dk):
            hidden = _apply(params, toks, dk, return_hidden=True)
            tgt = jnp.roll(toks, -1, axis=1)
            head = params["lm_head"]
            return fused_softmax_cross_entropy(
                hidden, head["kernel"], head["bias"], tgt,
                block_v=args.loss_chunk).mean()
    else:
        def loss_fn(params, toks, dk):
            logits = _apply(params, toks, dk)
            tgt = jnp.roll(toks, -1, axis=1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

    def one_step(params, opt_state, key, toks):
        if args.dropout:
            key, dk = jax.random.split(key)
        else:
            dk = key  # unused (rngs=None): the stock program keeps its
            # published shape — no live split in the scan body
        loss, g = jax.value_and_grad(loss_fn)(params, toks, dk)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, key, \
            hvd_jax.allreduce(loss)

    spc = max(1, args.steps_per_call)

    @hvd_jax.jit(in_specs=(P(), P(), P(), P(hvd_jax.HVD_AXIS)),
                 out_specs=(P(), P(), P(), P()), donate_argnums=(0, 1))
    def step(params, opt_state, key, toks):
        if spc == 1:
            return one_step(params, opt_state, key, toks)

        def body(carry, _):
            params, opt_state, key = carry
            params, opt_state, key, loss = one_step(params, opt_state,
                                                    key, toks)
            return (params, opt_state, key), loss

        (params, opt_state, key), losses = jax.lax.scan(
            body, (params, opt_state, key), None, length=spc,
            unroll=max(1, args.unroll))
        return params, opt_state, key, losses[-1]

    toks = jnp.asarray(tokens)
    # Per-PROCESS dropout stream: data-parallel replicas must not apply
    # correlated masks (chips within one controller still share a mask —
    # acceptable for a benchmark; per-chip streams would fold in
    # ops.axis_rank() inside the step).
    step_key = jax.random.fold_in(jax.random.PRNGKey(1), hvd.rank())
    # AOT compile: reuse the executable AND read XLA's own FLOP count so
    # the printout carries MFU (cost analysis counts a scan body once —
    # see bench.py for the on-chip verification of that invariant).
    flops_per_step = 0.0
    counted = 1  # scan steps cost_analysis holds (set with flops below)
    step_fn = step
    try:
        compiled = step.lower(params, opt_state, step_key,
                              toks).compile()
        step_fn = compiled
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        from horovod_tpu.utils.hardware import scan_cost_analysis_steps

        # Scan body + peeled remainder each counted once (bench.py's
        # on-chip-verified rule, shared via utils.hardware).
        counted = scan_cost_analysis_steps(spc, args.unroll)
        flops_per_step = float(ca.get("flops", 0.0)) / counted
    except Exception as exc:  # pragma: no cover
        print(f"# cost_analysis unavailable: {exc}", file=sys.stderr)

    ncalls_warm = max(1, args.warmup // spc)
    ncalls = max(1, args.steps // spc)
    nsteps = ncalls * spc
    for _ in range(ncalls_warm):
        params, opt_state, step_key, loss = step_fn(params, opt_state,
                                                    step_key, toks)
    # Real device->host fetch: block_until_ready is not an execution
    # barrier on the tunneled axon platform (see bench.py).
    float(np.asarray(loss))

    if args.profile:
        from horovod_tpu.utils import profiler

        with profiler.profile(args.profile):
            for _ in range(ncalls):
                params, opt_state, step_key, loss = step_fn(
                    params, opt_state, step_key, toks)
            float(np.asarray(loss))  # fetch barrier INSIDE the trace
        print(f"# profile: {len(profiler.trace_files(args.profile))} "
              f"xplane file(s) in {args.profile}", file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(ncalls):
        params, opt_state, step_key, loss = step_fn(params, opt_state,
                                                    step_key, toks)
    float(np.asarray(loss))
    dt = time.perf_counter() - t0
    step_time = dt / nsteps
    tok_per_sec = args.batch_size * args.seq_len / step_time
    seq_per_sec = args.batch_size / step_time
    from horovod_tpu.utils.hardware import peak_flops

    peak = peak_flops(jax.devices()[0])
    if peak and flops_per_step / step_time > peak:
        # Value was pre-divided by `counted`: recover one step's FLOPs as
        # raw/spc (the same over-peak guard rescale as bench.py).
        flops_per_step *= counted / spc
    mfu = flops_per_step / step_time / peak if peak and flops_per_step \
        else float("nan")
    print(f"tokens/sec/chip: {tok_per_sec:.0f}  "
          f"sequences/sec/chip: {seq_per_sec:.2f}  "
          f"step_ms: {step_time*1e3:.2f}  mfu: {mfu:.3f}  "
          f"loss={float(loss):.3f}")


if __name__ == "__main__":
    main()
