#!/usr/bin/env python
"""MNIST with the full callback suite + checkpoint/resume (reference:
examples/keras_mnist_advanced.py): warmup, lr schedule with momentum
correction, metric averaging, resume-from-latest-checkpoint with the
restored epoch broadcast from rank 0.

Run: PYTHONPATH=. python examples/keras_mnist_advanced.py --epochs 4
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import optax

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
import horovod_tpu.keras as hvd_keras
from horovod_tpu.keras.callbacks import (
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
from horovod_tpu.models import MnistConvNet
from horovod_tpu.utils import latest_checkpoint

from common import synthetic_mnist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--warmup-epochs", type=int, default=2)
    ap.add_argument("--checkpoint-dir", default="")
    args = ap.parse_args()

    hvd.init()
    ckpt_dir = args.checkpoint_dir or os.path.join(
        tempfile.gettempdir(), "hvd_keras_advanced")
    (xtr, ytr), (xte, yte) = synthetic_mnist()

    trainer = hvd_keras.Trainer(
        MnistConvNet(), optax.sgd(0.01 * hvd.size(), momentum=0.9))

    # Resume: restored epoch decided by rank 0 and broadcast (reference:
    # keras_imagenet_resnet50.py:73,102-103).
    resume_epoch = 0
    ckpt = latest_checkpoint(ckpt_dir)
    if ckpt:
        trainer.load(ckpt, xtr[:args.batch_size])
        resume_epoch = int(hvd_jax.broadcast_object(
            trainer._epoch + 1, root_rank=0))
        print(f"resuming from epoch {resume_epoch}")

    callbacks = [
        BroadcastGlobalVariablesCallback(0),
        MetricAverageCallback(),
        LearningRateWarmupCallback(warmup_epochs=args.warmup_epochs,
                                   verbose=1),
        LearningRateScheduleCallback(
            multiplier=lambda e: 0.5 ** max(0, e - args.warmup_epochs),
            start_epoch=args.warmup_epochs),
    ]
    hist = trainer.fit(xtr, ytr, batch_size=args.batch_size,
                       epochs=args.epochs, callbacks=callbacks,
                       initial_epoch=resume_epoch,
                       validation_data=(xte, yte), verbose=1)
    trainer.save(ckpt_dir)
    if hist.get("loss"):
        assert hist["loss"][-1] < 2.5


if __name__ == "__main__":
    main()
