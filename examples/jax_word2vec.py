#!/usr/bin/env python
"""word2vec skip-gram with negative sampling (reference:
examples/tensorflow_word2vec.py): each rank samples its own skip-gram
batches from the token stream; gradients average across ranks.

Run: PYTHONPATH=. python examples/jax_word2vec.py --steps 50
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
from horovod_tpu.models import Word2Vec

from common import synthetic_text


def skipgram_batches(tokens, batch, window, k_neg, vocab, seed):
    rng = np.random.RandomState(seed)
    while True:
        centers = rng.randint(window, len(tokens) - window, size=batch)
        offs = rng.randint(1, window + 1, size=batch)
        signs = rng.choice([-1, 1], size=batch)
        ctx = tokens[centers + offs * signs]
        negs = rng.randint(0, vocab, size=(batch, k_neg))
        yield tokens[centers], ctx, negs.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=128,
                    help="per-chip batch")
    ap.add_argument("--embedding-dim", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--window", type=int, default=2)
    ap.add_argument("--negatives", type=int, default=5)
    args = ap.parse_args()

    hvd.init()
    tokens = synthetic_text(vocab=args.vocab)
    model = Word2Vec(vocab_size=args.vocab,
                     embedding_dim=args.embedding_dim)
    opt = hvd_jax.DistributedOptimizer(optax.adagrad(0.5))

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((4,), jnp.int32))
    params = hvd_jax.broadcast_parameters(variables["params"])
    opt_state = opt.init(params)

    def loss_fn(params, c, x, n):
        return model.apply({"params": params}, c, x, n,
                           method=model.neg_loss)

    @hvd_jax.jit(in_specs=(P(), P(), P(hvd_jax.HVD_AXIS),
                           P(hvd_jax.HVD_AXIS), P(hvd_jax.HVD_AXIS)),
                 out_specs=(P(), P(), P()))
    def step(params, opt_state, c, x, n):
        loss, g = jax.value_and_grad(loss_fn)(params, c, x, n)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            hvd_jax.allreduce(loss)

    gen = skipgram_batches(tokens, args.batch_size * hvd.local_size(),
                           args.window, args.negatives, args.vocab,
                           seed=hvd.rank())
    first = last = None
    for s in range(args.steps):
        c, x, n = next(gen)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(c), jnp.asarray(x),
            jnp.asarray(n))
        if s == 0:
            first = float(loss)
        last = float(loss)
        if s % 20 == 0:
            print(f"step {s}: loss={last:.4f}")
    print(f"final loss: {last:.4f}")
    assert last < first


if __name__ == "__main__":
    main()
