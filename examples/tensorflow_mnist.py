#!/usr/bin/env python
"""MNIST with the TensorFlow frontend — a mechanical port of the reference
example (reference: examples/tensorflow_mnist.py): same convnet, same
DistributedOptimizer + broadcast integration, TF2 eager style. TF computes
on host CPU; collectives ride the XLA mesh.

Run: PYTHONPATH=. python examples/tensorflow_mnist.py --steps 30
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import horovod_tpu.tensorflow as hvd

from common import synthetic_mnist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args()

    import tensorflow as tf

    # Deterministic init/dropout: the few-step smoke assertion below
    # (loss decreased) is otherwise a coin flip on unlucky draws.
    tf.keras.utils.set_random_seed(0)

    hvd.init()
    (xtr, ytr), _ = synthetic_mnist()

    # The reference's 2-layer convnet (tensorflow_mnist.py:30-63).
    model = tf.keras.Sequential([
        tf.keras.layers.Reshape((28, 28, 1), input_shape=(28, 28, 1)),
        tf.keras.layers.Conv2D(32, 5, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Conv2D(64, 5, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(1024, activation="relu"),
        tf.keras.layers.Dropout(0.5),
        tf.keras.layers.Dense(10),
    ])
    # lr scaled by size, optimizer wrapped (reference: :85-90).
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.Adam(args.lr * hvd.size()))
    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    first = last = None
    for step in range(args.steps):
        i = (step * args.batch_size) % (len(xtr) - args.batch_size)
        x = tf.constant(xtr[i:i + args.batch_size])
        y = tf.constant(ytr[i:i + args.batch_size].astype(np.int64))
        with tf.GradientTape() as tape:
            loss = loss_obj(y, model(x, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if step == 0:
            # Broadcast initial state after the first step creates slots
            # (reference: BroadcastGlobalVariablesHook after_create_session).
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
            first = float(loss)
        last = float(loss)
        if step % 10 == 0:
            print(f"step {step}: loss={last:.4f}")
    assert last < first, (first, last)


if __name__ == "__main__":
    main()
