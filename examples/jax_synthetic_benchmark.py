#!/usr/bin/env python
"""Synthetic model benchmark — img/sec per chip, mean ± 1.96σ (reference:
examples/tensorflow_synthetic_benchmark.py). ResNet-50 by default; any
model in horovod_tpu.models via --model.

Run: PYTHONPATH=. python examples/jax_synthetic_benchmark.py --model resnet50
"""

import argparse
import subprocess
import sys
import os


def main():
    # bench.py at the repo root is the canonical implementation; this
    # wrapper keeps the reference's examples/ entry point.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.exit(subprocess.call(
        [sys.executable, os.path.join(root, "bench.py")] + sys.argv[1:]))


if __name__ == "__main__":
    main()
