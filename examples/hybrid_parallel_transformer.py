#!/usr/bin/env python
"""4D hybrid-parallel transformer training (dp × pp × tp × sp) — beyond the
reference's data-parallel-only scope: GPipe pipeline stages, Megatron
tensor-parallel projections, ring attention over the sequence axis.

Run: PYTHONPATH=. python examples/hybrid_parallel_transformer.py
"""

import argparse

import jax

from horovod_tpu.parallel import hybrid


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="0 = all visible devices")
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=32)
    args = ap.parse_args()

    n = args.devices or len(jax.devices())
    sizes = hybrid.partition_axes(n)
    print(f"devices={n} mesh={sizes}")
    cfg = hybrid.HybridConfig(seq_len=args.seq_len,
                              hidden_dim=args.hidden)
    l0, l1 = hybrid.dryrun(n, cfg=cfg)
    print(f"one hybrid step: loss {l0:.4f} -> {l1:.4f}")
    assert l1 < l0


if __name__ == "__main__":
    main()
