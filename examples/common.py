"""Shared example helpers: synthetic datasets (this environment has no
network egress, so examples default to synthetic data the way the
reference's synthetic benchmarks do — reference:
examples/tensorflow_synthetic_benchmark.py:56-60)."""

import numpy as np


def synthetic_mnist(n=2048, seed=0):
    """Learnable stand-in for MNIST: labels derive from a fixed random
    projection of the pixels, so training curves are meaningful."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int32)
    return (x[: n * 3 // 4], y[: n * 3 // 4]), (x[n * 3 // 4:], y[n * 3 // 4:])


def synthetic_imagenet(n=256, size=224, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, size, size, 3).astype(np.float32)
    y = rng.randint(0, 1000, size=(n,)).astype(np.int32)
    return x, y


def synthetic_text(n_tokens=65536, vocab=1000, seed=0):
    """Zipf-ish token stream for word2vec / LM examples."""
    rng = np.random.RandomState(seed)
    p = 1.0 / np.arange(1, vocab + 1)
    p /= p.sum()
    return rng.choice(vocab, size=n_tokens, p=p).astype(np.int32)


def shard_batch(a, mesh, axis_name):
    """Split a host batch across this process's devices and assemble the
    global [per * world_size, ...] array every example feeds its step
    (the shared form of the per-example `shard` helpers)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd

    per = a.shape[0] // hvd.local_size()
    shards = [jax.device_put(a[i * per:(i + 1) * per], d)
              for i, d in enumerate(mesh.local_mesh.devices.flat)]
    return jax.make_array_from_single_device_arrays(
        (per * hvd.size(),) + a.shape[1:],
        NamedSharding(mesh, P(axis_name)), shards)
