#!/usr/bin/env python
"""ResNet-50 ImageNet-style torch training (reference:
examples/pytorch_imagenet_resnet50.py): gradient accumulation via
batches-per-allreduce, warmup LR schedule, checkpoint/resume with the
resume epoch decided on rank 0, distributed metric averaging.

Run: PYTHONPATH=. python examples/pytorch_imagenet_resnet50.py --epochs 1 \
         --steps 4
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
import torchvision_stub
from horovod_tpu.utils import Metric


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--batches-per-allreduce", type=int, default=2,
                    help="gradient accumulation (reference: :140-144)")
    ap.add_argument("--base-lr", type=float, default=0.0125)
    ap.add_argument("--warmup-epochs", type=int, default=5)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--checkpoint-dir", default="")
    args = ap.parse_args()

    hvd.init()
    ckpt_dir = args.checkpoint_dir or os.path.join(
        tempfile.gettempdir(), "hvd_torch_r50")
    os.makedirs(ckpt_dir, exist_ok=True)
    ckpt_format = os.path.join(ckpt_dir, "checkpoint-{epoch}.pt")

    # Resume from the latest checkpoint on rank 0; epoch broadcast to all
    # (reference: pytorch_imagenet_resnet50.py:70-80,135-143).
    resume_from_epoch = 0
    for try_epoch in range(args.epochs, 0, -1):
        if os.path.exists(ckpt_format.format(epoch=try_epoch)):
            resume_from_epoch = try_epoch
            break
    resume_from_epoch = int(hvd.broadcast(
        torch.tensor(resume_from_epoch), root_rank=0,
        name="resume_from_epoch").item())

    model = torchvision_stub.get_model("resnet50")
    lr_scaler = args.batches_per_allreduce * hvd.size()
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.base_lr * lr_scaler, momentum=0.9)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        backward_passes_per_step=args.batches_per_allreduce)

    if resume_from_epoch > 0 and hvd.rank() == 0:
        ckpt = torch.load(ckpt_format.format(epoch=resume_from_epoch))
        model.load_state_dict(ckpt["model"])
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    data = torch.randn(args.batch_size, 3, 64, 64)
    target = torch.randint(0, 1000, (args.batch_size,))

    def adjust_lr(epoch, batch, steps):
        # Reference warmup formula (pytorch_imagenet_resnet50.py:178-190).
        if epoch < args.warmup_epochs:
            ep = epoch + float(batch + 1) / steps
            adj = 1.0 / hvd.size() * (
                ep * (hvd.size() - 1) / args.warmup_epochs + 1)
        else:
            adj = 0.1 ** ((epoch - args.warmup_epochs) // 30 + 0)
            adj = max(adj, 1e-3)
        for g in optimizer.param_groups:
            g["lr"] = args.base_lr * lr_scaler * adj

    model.train()
    import time

    sync_s = 0.0  # time inside optimizer.step() = allreduce drain point
    t_train0 = time.perf_counter()
    for epoch in range(resume_from_epoch, args.epochs):
        train_loss = Metric("train_loss")
        for b in range(args.steps):
            adjust_lr(epoch, b, args.steps)
            optimizer.zero_grad()
            for _ in range(args.batches_per_allreduce):
                loss = F.cross_entropy(model(data), target)
                train_loss.update(loss.item())
                (loss / args.batches_per_allreduce).backward()
            t0 = time.perf_counter()
            optimizer.step()
            sync_s += time.perf_counter() - t0
        print(f"epoch {epoch}: train_loss={train_loss.avg:.4f} "
              f"(averaged over {hvd.size()} ranks)")
        if hvd.rank() == 0:
            torch.save({"model": model.state_dict()},
                       ckpt_format.format(epoch=epoch + 1))
    dt = time.perf_counter() - t_train0
    nimg = ((args.epochs - resume_from_epoch) * args.steps
            * args.batch_size * args.batches_per_allreduce)
    if dt > 0 and nimg:
        # NB: forward/backward run on host-CPU torch; this measures the
        # engine-path integration, not TPU compute (see docs/concepts.md
        # "Differences from Horovod" #2).
        print(f"images/sec: {nimg / dt:.1f}  "
              f"allreduce-sync share: {100 * sync_s / dt:.0f}% of step")


if __name__ == "__main__":
    main()
