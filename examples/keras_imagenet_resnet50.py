#!/usr/bin/env python
"""ResNet-50 ImageNet-style training through the Trainer (reference:
examples/keras_imagenet_resnet50.py): warmup over 5 epochs, 30/60/80
stepwise decay, checkpoint/resume, metric averaging. Synthetic data by
default (no egress).

Run: PYTHONPATH=. python examples/keras_imagenet_resnet50.py --epochs 1 \
         --steps 4 --image-size 64
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import optax

import horovod_tpu as hvd
import horovod_tpu.keras as hvd_keras
from horovod_tpu.keras.callbacks import (
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
from horovod_tpu.models import ResNet50

from common import synthetic_imagenet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=90)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--base-lr", type=float, default=0.0125)
    ap.add_argument("--warmup-epochs", type=int, default=5)
    ap.add_argument("--steps", type=int, default=8,
                    help="train steps per epoch (synthetic)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--checkpoint-dir", default="")
    args = ap.parse_args()

    hvd.init()
    n = args.steps * args.batch_size * hvd.local_size()
    x, y = synthetic_imagenet(n=n, size=args.image_size)
    import jax.numpy as jnp

    # Feed bf16: the model computes in bf16, and halving the host->device
    # bytes matters wherever the feed link is the bottleneck (bench.py
    # does the same; measured 2x on the tunneled chip).
    x = x.astype(jnp.bfloat16)

    trainer = hvd_keras.Trainer(
        ResNet50(),
        # Reference: base_lr scaled by size, SGD momentum 0.9
        # (keras_imagenet_resnet50.py:117-120).
        optax.sgd(args.base_lr * hvd.size(), momentum=0.9))

    callbacks = [
        BroadcastGlobalVariablesCallback(0),
        MetricAverageCallback(),
        LearningRateWarmupCallback(warmup_epochs=args.warmup_epochs,
                                   verbose=1),
        # Reference decay schedule: 30/60/80 (keras_imagenet_resnet50.py:
        # 124-127).
        LearningRateScheduleCallback(1.0, start_epoch=args.warmup_epochs,
                                     end_epoch=30),
        LearningRateScheduleCallback(1e-1, start_epoch=30, end_epoch=60),
        LearningRateScheduleCallback(1e-2, start_epoch=60, end_epoch=80),
        LearningRateScheduleCallback(1e-3, start_epoch=80),
    ]
    import time

    hist = trainer.fit(x, y, batch_size=args.batch_size, epochs=1,
                       callbacks=callbacks, verbose=1)  # compile warmup
    t0 = time.perf_counter()
    hist = trainer.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
                       callbacks=callbacks, verbose=1)
    dt = time.perf_counter() - t0
    images = args.steps * args.batch_size * args.epochs
    print(f"images/sec/chip: {images / dt:.1f} "
          f"(keras trainer path, {hvd.size()} chip(s))")
    if args.checkpoint_dir:
        trainer.save(args.checkpoint_dir)
    assert "loss" in hist


if __name__ == "__main__":
    main()
