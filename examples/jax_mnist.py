#!/usr/bin/env python
"""MNIST with the JAX frontend — the TPU-native analogue of the reference's
flagship example (reference: examples/tensorflow_mnist.py): hvd.init, the
2-layer convnet, DistributedOptimizer, startup broadcast, rank-0-only
checkpointing.

Run: PYTHONPATH=. python examples/jax_mnist.py --epochs 2 --synthetic
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
from horovod_tpu.models import MnistConvNet
from horovod_tpu.utils import save_checkpoint

from common import shard_batch, synthetic_mnist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-chip batch size")
    ap.add_argument("--lr", type=float, default=0.001)
    ap.add_argument("--synthetic", action="store_true", default=True)
    ap.add_argument("--checkpoint-dir", default="")
    args = ap.parse_args()

    hvd.init()
    (xtr, ytr), (xte, yte) = synthetic_mnist()

    model = MnistConvNet(dtype=jnp.float32)
    # Scale the learning rate by world size, as the reference example does
    # (reference: tensorflow_mnist.py:85 `lr * hvd.size()`).
    opt = hvd_jax.DistributedOptimizer(optax.adam(args.lr * hvd.size()))

    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(xtr[:8]), False)
    params = hvd_jax.broadcast_parameters(variables["params"], root_rank=0)
    opt_state = opt.init(params)

    def loss_fn(params, x, y, key):
        logits = model.apply({"params": params}, x, True,
                             rngs={"dropout": key})
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    # Donate params/opt_state: both are rebound to the step's outputs, so
    # XLA updates them in place instead of paying a copy-on-update of
    # every param-sized buffer each step.
    @hvd_jax.jit(in_specs=(P(), P(), P(hvd_jax.HVD_AXIS),
                           P(hvd_jax.HVD_AXIS), P()),
                 out_specs=(P(), P(), P()),
                 donate_argnums=(0, 1))
    def train_step(params, opt_state, x, y, key):
        loss, g = jax.value_and_grad(loss_fn)(params, x, y, key)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            hvd_jax.allreduce(loss)

    mesh = hvd.mesh()

    def shard(a):
        return shard_batch(a, mesh, hvd_jax.HVD_AXIS)

    n_local = args.batch_size * hvd.local_size()
    steps = len(xtr) // n_local
    key = jax.random.PRNGKey(hvd.rank())
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(steps * n_local)
        for s in range(steps):
            sel = perm[s * n_local:(s + 1) * n_local]
            key, dk = jax.random.split(key)
            params, opt_state, loss = train_step(
                params, opt_state, shard(xtr[sel]), shard(ytr[sel]), dk)
        # Rank-0-only checkpoint write (reference pattern:
        # tensorflow_mnist.py:104-107 checkpoint_dir gated on rank 0).
        if args.checkpoint_dir:
            save_checkpoint(args.checkpoint_dir, {"params": params}, epoch)
        print(f"epoch {epoch}: loss={float(loss):.4f}")

    # Eval on the replicated model.
    logits = model.apply({"params": params}, jnp.asarray(xte), False)
    acc = float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(yte)))
    print(f"test accuracy: {acc:.3f}")
    assert float(loss) < 2.0, "training did not reduce the loss"


if __name__ == "__main__":
    main()
