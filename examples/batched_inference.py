#!/usr/bin/env python
"""Data-parallel batched inference on the serving plane.

The mixed train+serve shape from docs/running.md "Serving plane": eval
batches are sharded over the world mesh and scored by a compiled forward
pass (the hot path needs no engine), while the per-batch serving metric —
a class histogram every rank must agree on — rides the ENGINE as a
``priority='high'`` allreduce with a per-request deadline. Background
training-style traffic (big, ``priority='low'`` gradient-sized buffers)
runs concurrently; the scheduler drains the high class first, so serving
latency stays bounded no matter how much bulk work is queued behind it.

Each request carries a client budget: if the metric reduction has not
completed within ``--client-timeout-ms`` the client walks away and the
request is cooperatively cancelled (``Engine.cancel`` — the PR 15
doctrine: cancellation at a safe point, never mid-collective). Admission
state (queue depth, per-class in-flight vs budgets) is printed at the
end — the same body ``/healthz`` serves.

Run: PYTHONPATH=. python examples/batched_inference.py --batches 8
Multi-process:
    python -m horovod_tpu.run -np 2 --cpu -- python \
        examples/batched_inference.py --batches 8
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu.core.engine import (
    CollectiveTimeout,
    admission_summary,
    get_engine,
)
from horovod_tpu.jax import mpi_ops
from horovod_tpu.ops.collectives import HVD_AXIS

from common import shard_batch, synthetic_mnist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=8,
                    help="eval batches to serve")
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-chip eval batch size")
    ap.add_argument("--deadline-ms", type=float, default=5000.0,
                    help="engine-side deadline on the metric reduction")
    ap.add_argument("--client-timeout-ms", type=float, default=4000.0,
                    help="client walk-away budget; overdue requests are "
                         "cooperatively cancelled")
    ap.add_argument("--background-mb", type=float, default=4.0,
                    help="size of the concurrent low-priority training "
                         "buffer (0 disables the mixed-load shape)")
    args = ap.parse_args()

    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    eng = get_engine()
    mesh = hvd.mesh()

    (_, _), (xte, yte) = synthetic_mnist()
    # A fixed random projection scored by a compiled, sharded forward
    # pass — the model itself is beside the point; the serving plumbing
    # around it is the example.
    w = np.random.RandomState(1).randn(784, 10).astype(np.float32)
    w = mpi_ops.broadcast(w, root_rank=0, name="serve.model.w")

    @jax.jit
    def forward(wp, x):
        return jnp.argmax(x.reshape(x.shape[0], -1) @ wp, axis=-1)

    wp = jnp.asarray(w)
    per_global = args.batch_size * hvd.local_size()

    served = cancelled = timed_out = 0
    latencies_ms = []
    bg_handle = None
    for b in range(args.batches):
        lo = (b * per_global) % max(1, len(xte) - per_global)
        batch = shard_batch(xte[lo:lo + per_global], mesh, HVD_AXIS)
        preds = np.asarray(jax.device_get(forward(wp, batch)))

        # Bulk work queued BEHIND the serving request: a training-sized
        # low-class buffer per batch (fire-and-forget, drained at exit).
        if args.background_mb > 0 and bg_handle is None:
            n = int(args.background_mb * 1e6 / 4)
            bg_handle = mpi_ops.allreduce_async(
                np.ones(n, dtype=np.float32), name="serve.background",
                priority="low", deadline_ms=30000)

        hist = np.bincount(preds, minlength=10).astype(np.float64)
        t0 = time.monotonic()
        h = mpi_ops.allreduce_async(
            hist, average=False, name=f"serve.metric.{b}",
            priority="high", deadline_ms=args.deadline_ms)
        # The client polls with its own budget; on walk-away the request
        # is cancelled so it stops holding an admission slot.
        while not eng.poll(h):
            if (time.monotonic() - t0) * 1e3 > args.client_timeout_ms:
                break
            time.sleep(0.001)
        if eng.poll(h):
            try:
                global_hist = mpi_ops.synchronize(h)
            except CollectiveTimeout:
                timed_out += 1
                continue
            latencies_ms.append((time.monotonic() - t0) * 1e3)
            served += 1
            if rank == 0 and b == 0:
                top = int(np.argmax(global_hist))
                print(f"batch {b}: served {int(global_hist.sum())} "
                      f"examples across {world} rank(s), modal class "
                      f"{top}", flush=True)
        else:
            eng.cancel(h)
            cancelled += 1
            try:
                mpi_ops.synchronize(h)
            except Exception:
                pass  # cancelled/overdue — the client already left

        if bg_handle is not None and eng.poll(bg_handle):
            mpi_ops.synchronize(bg_handle)
            bg_handle = None

    if bg_handle is not None:
        try:
            mpi_ops.synchronize(bg_handle)
        except Exception:
            pass

    adm = admission_summary() or {}
    p50 = (sorted(latencies_ms)[len(latencies_ms) // 2]
           if latencies_ms else None)
    print(f"rank {rank}: served={served} cancelled={cancelled} "
          f"timed_out={timed_out} p50_ms="
          f"{p50 if p50 is None else round(p50, 2)} "
          f"queue_depth={adm.get('queue_depth')} "
          f"saturated={adm.get('saturated')}", flush=True)
    hvd.shutdown()
    sys.exit(0 if served > 0 else 1)


if __name__ == "__main__":
    main()
