"""The reference's tf.estimator example, ported to surviving TF APIs.

Reference: examples/tensorflow_mnist_estimator.py:1-214 — a CNN
``model_fn`` returning ``EstimatorSpec``, trained by ``Estimator.train``
with ``hvd.BroadcastGlobalVariablesHook(0)``, checkpoints written by
rank 0 only, ``steps // hvd.size()`` scaling, then ``evaluate``.

``tf.estimator`` itself was REMOVED from TensorFlow in 2.16 (this
environment ships 2.21: neither ``tf.estimator`` nor the
``tensorflow_estimator`` package exists), so a literal port cannot run
on any modern TF. This script preserves the example's shape — the part
a user migrating an estimator codebase actually keeps — on the
session-era APIs this framework supports unmodified:

==============================================  =============================
reference (estimator)                           here (v1 session)
==============================================  =============================
``cnn_model_fn(features, labels, mode)``        ``cnn_model_fn`` (same
  -> ``tf.estimator.EstimatorSpec``               signature) -> ``_Spec``
``hvd.BroadcastGlobalVariablesHook(0)``         same hook, same position
``opt = hvd.DistributedOptimizer(opt)``         same wrapper
``Estimator(model_fn, model_dir=rank0_only)``   ``CheckpointSaverHook`` on
                                                  rank 0 only
``train(steps=20000 // hvd.size(), hooks=...)`` counted train loop of
                                                  ``steps // size``
``evaluate(input_fn)``                          eval graph + metric ops run
                                                  after training
==============================================  =============================

Run (any -np; synthetic data by default — this sandbox has no egress):

    python -m horovod_tpu.run -np 2 --cpu -- \
        python examples/tensorflow_mnist_estimator.py --steps 40
"""

import argparse
import collections
import os
import tempfile

import numpy as np

_Spec = collections.namedtuple(
    "EstimatorSpec", ["mode", "loss", "train_op", "eval_metric_ops"])
_TRAIN, _EVAL = "train", "eval"


def cnn_model_fn(features, labels, mode, lr=0.001):
    """The reference's model function (conv5x5/32 - pool - conv5x5/64 -
    pool - dense1024 - logits10, reference :32-107), at the same
    signature, on tf.compat.v1 primitives."""
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    tf1 = tf.compat.v1

    # Seeded init: the smoke tier asserts loss decreases within a few
    # steps, which an unlucky unseeded glorot draw can flake.
    def conv(x, name, cout, cin, seed):
        w = tf1.get_variable(
            name + "_w", [5, 5, cin, cout],
            initializer=tf1.glorot_uniform_initializer(seed=seed))
        b = tf1.get_variable(name + "_b", [cout],
                             initializer=tf1.zeros_initializer())
        y = tf.nn.conv2d(x, w, strides=1, padding="SAME") + b
        return tf.nn.max_pool2d(tf.nn.relu(y), 2, 2, "VALID")

    x = tf.reshape(features["x"], [-1, 28, 28, 1])
    x = conv(x, "conv1", 8, 1, seed=41)
    x = conv(x, "conv2", 16, 8, seed=42)
    x = tf.reshape(x, [-1, 7 * 7 * 16])
    wd = tf1.get_variable(
        "dense_w", [7 * 7 * 16, 10],
        initializer=tf1.glorot_uniform_initializer(seed=43))
    bd = tf1.get_variable("dense_b", [10],
                          initializer=tf1.zeros_initializer())
    logits = tf.matmul(x, wd) + bd
    loss = tf.reduce_mean(
        tf.nn.sparse_softmax_cross_entropy_with_logits(
            labels=labels, logits=logits))

    if mode == _TRAIN:
        # The reference scales LR by size and wraps with
        # DistributedOptimizer (reference :116-124).
        opt = tf1.train.GradientDescentOptimizer(lr * hvd.size())
        opt = hvd.DistributedOptimizer(opt)
        step = tf1.train.get_or_create_global_step()
        return _Spec(mode, loss, opt.minimize(loss, global_step=step), None)

    acc = tf1.metrics.accuracy(
        labels=labels, predictions=tf.argmax(logits, axis=1))
    return _Spec(mode, loss, None, {"accuracy": acc})


def _data(n, seed):
    """Synthetic MNIST-shaped digits: class = quadrant with the bright
    blob, learnable in a few dozen steps."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 4, size=n).astype(np.int32)
    imgs = rng.rand(n, 28, 28).astype(np.float32) * 0.2
    for i, c in enumerate(labels):
        r, q = divmod(int(c), 2)
        imgs[i, 14 * r:14 * r + 14, 14 * q:14 * q + 14] += 0.8
    return imgs.reshape(n, 784), labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200,
                    help="TOTAL train steps; divided by world size like "
                         "the reference's 20000 // hvd.size()")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.001)
    ap.add_argument("--model-dir", default="",
                    help="checkpoint dir (rank 0 writes; default: temp)")
    args = ap.parse_args()

    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    tf1 = tf.compat.v1
    hvd.init()

    xs, ys = _data(4096, seed=hvd.rank())
    exs, eys = _data(512, seed=99)  # same eval set on every rank

    # Rank-0-only model_dir — the reference's corruption guard (:173-175).
    model_dir = (args.model_dir or os.path.join(
        tempfile.gettempdir(), "mnist_estimator_model")
        if hvd.rank() == 0 else None)

    graph = tf.Graph()
    with graph.as_default():
        images = tf1.placeholder(tf.float32, [None, 784], name="image")
        labels = tf1.placeholder(tf.int32, [None], name="label")
        spec = cnn_model_fn({"x": images}, labels, _TRAIN, lr=args.lr)
        with tf1.variable_scope("", reuse=True):
            eval_spec = cnn_model_fn({"x": images}, labels, _EVAL)
        # Metric state is v1 "local" variables; the init op must exist
        # before MonitoredTrainingSession finalizes the graph.
        local_init = tf1.local_variables_initializer()

        hooks = [hvd.BroadcastGlobalVariablesHook(0)]
        if model_dir:
            os.makedirs(model_dir, exist_ok=True)
            hooks.append(tf1.train.CheckpointSaverHook(
                model_dir, save_steps=max(1, args.steps // hvd.size())))

        rng = np.random.RandomState(0)
        losses = []
        with tf1.train.MonitoredTrainingSession(hooks=hooks) as sess:
            # Counted loop, not StopAtStepHook: the estimator ran
            # evaluate() after train() in the same process, and a
            # triggered stop hook forbids the eval sess.run calls below
            # (the hook itself is exercised by tensorflow_mnist.py and
            # the frontend suite).
            for _ in range(max(1, args.steps // hvd.size())):
                sel = rng.randint(0, len(xs), size=args.batch_size)
                _, lv = sess.run([spec.train_op, spec.loss],
                                 feed_dict={images: xs[sel],
                                            labels: ys[sel]})
                losses.append(lv)
            # Evaluate inside the managed session (variables live here);
            # the estimator's evaluate() ran a fresh metric pass.
            sess.run(local_init)
            _, acc_op = eval_spec.eval_metric_ops["accuracy"]
            for i in range(0, len(exs), args.batch_size):
                acc = sess.run(acc_op,
                               feed_dict={images: exs[i:i + args.batch_size],
                                          labels: eys[i:i + args.batch_size]})
    print(f"rank {hvd.rank()}/{hvd.size()}: {len(losses)} steps, "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f}, eval acc {acc:.3f}")
    assert losses[-1] < losses[0], "did not train"


if __name__ == "__main__":
    main()
