#!/usr/bin/env bash
# Build the horovod_tpu container image (the reference's
# build-docker-images.sh role, one target instead of a CUDA matrix —
# TPU capability lives in the jax[tpu] wheel, not the image flavor).
set -euo pipefail
cd "$(dirname "$0")"

TAG="${1:-horovod-tpu:latest}"
docker build -t "$TAG" .
echo "built $TAG — smoke it with:"
echo "  docker run --privileged --network host $TAG"
