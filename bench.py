#!/usr/bin/env python
"""Synthetic ResNet-50 training benchmark — the driver's headline metric.

Methodology mirrors the reference's synthetic benchmark (reference:
examples/tensorflow_synthetic_benchmark.py:17-28,77-106): random data,
``DistributedOptimizer`` training step, N warmup batches, then
``num_iters x num_batches_per_iter`` timed steps, reporting images/sec per
chip as mean ± 1.96σ.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

vs_baseline compares against the only absolute throughput figure published in
the reference tree: 1656.82 images/sec on 16 GPUs (ResNet-101,
docs/benchmarks.md:33-38) → 103.55 images/sec per device.
"""

import argparse
import json
import sys
import time

BASELINE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # reference docs/benchmarks.md:33-38


def main():
    p = argparse.ArgumentParser(description="horovod_tpu synthetic benchmark")
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch size (reference default 32)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="bf16 gradient compression on the wire")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax
    from horovod_tpu import models

    hvd.init()
    nchips = hvd.size()

    model = models.get_model(args.model)
    compression = (hvd_jax.Compression.fp16 if args.fp16_allreduce
                   else hvd_jax.Compression.none)
    opt = hvd_jax.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression)

    rng = jax.random.PRNGKey(0)
    images_host = np.random.uniform(
        size=(args.batch_size, args.image_size, args.image_size, 3)
    ).astype(np.float32)
    labels_host = np.random.randint(0, 1000, size=(args.batch_size,))

    variables = model.init(rng, jnp.asarray(images_host), False)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})
    opt_state = opt.init(params)
    # Startup sync, as every reference example does before training
    # (reference: BroadcastGlobalVariablesHook).
    params = hvd_jax.broadcast_parameters(params, root_rank=0)

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images, True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, mutated["batch_stats"]

    @hvd_jax.jit(
        in_specs=(P(), P(), P(), P(hvd_jax.HVD_AXIS), P(hvd_jax.HVD_AXIS)),
        out_specs=(P(), P(), P(), P()),
        donate_argnums=(0, 1, 2),
    )
    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_bs, opt_state, hvd_jax.allreduce(loss)

    # Each chip sees the full per-chip batch: global batch = B * size.
    mesh = hvd.mesh()
    from jax.sharding import NamedSharding

    def chip_batch(x):
        shards = [jax.device_put(x, d) for d in jax.local_devices()
                  if d in mesh.devices.flat]
        global_shape = (x.shape[0] * nchips,) + x.shape[1:]
        return jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, P(hvd_jax.HVD_AXIS)), shards)

    images = chip_batch(images_host)
    labels = chip_batch(labels_host)

    def run_batches(n):
        nonlocal params, batch_stats, opt_state
        loss = None
        for _ in range(n):
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, images, labels)
        jax.block_until_ready(loss)

    run_batches(args.num_warmup_batches)

    rates = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        run_batches(args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        rates.append(args.batch_size * args.num_batches_per_iter / dt)

    per_chip = float(np.mean(rates))
    result = {
        "metric": f"{args.model}_train_images_per_sec_per_chip"
                  f"_bs{args.batch_size}",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_DEVICE, 3),
    }
    print(json.dumps(result))
    print(f"# {nchips} chip(s), ±{1.96 * float(np.std(rates)):.1f} img/sec, "
          f"platform={jax.devices()[0].platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
