#!/usr/bin/env python
"""Synthetic training benchmark — the driver's headline metric.

Methodology mirrors the reference's synthetic benchmark (reference:
examples/tensorflow_synthetic_benchmark.py:17-28,77-106): random data,
``DistributedOptimizer`` training step, N warmup batches, then
``num_iters x num_batches_per_iter`` timed steps, reporting images/sec per
chip.

Timing is honest: each timed window ends with a real device->host fetch of
the loss (``float(np.asarray(loss))``) — on the tunneled ``axon`` platform
``jax.block_until_ready`` does NOT act as an execution barrier, so a fetch
is the only trustworthy fence.  The JSON line also reports per-step FLOPs
from XLA's own cost analysis and the implied MFU against the chip's peak,
so a physically impossible number is self-evident.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "step_time_ms": ..., "gflops_per_step": ..., "mfu": ...}

vs_baseline compares against the only absolute throughput figure published in
the reference tree: 1656.82 images/sec on 16 GPUs (ResNet-101,
docs/benchmarks.md:33-38) -> 103.55 images/sec per device.
"""

import argparse
import json
import os
import sys
import time

BASELINE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # reference docs/benchmarks.md:33-38


def main():
    p = argparse.ArgumentParser(description="horovod_tpu synthetic benchmark")
    p.add_argument("--model", default="resnet50")
    p.add_argument("--stem", default=None,
                   choices=["conv7", "space_to_depth"],
                   help="ResNet stem: classic 7x7/s2 conv, or the exact "
                        "space-to-depth reparameterization (MXU-friendly; "
                        "see models/resnet.py)")
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch size (reference default 32)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup-batches", type=int, default=150)
    p.add_argument("--num-batches-per-iter", type=int, default=800,
                   help="batches per timed window; each window ends in one "
                        "device->host fetch (the honesty barrier), so the "
                        "window must be long enough to amortize the "
                        "fetch+dispatch round-trip (~100 ms through the "
                        "tunnel — 4%% of a 200-step window, <1.5%% at "
                        "800; a real TPU host pays ~1 ms and would not "
                        "care)")
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--steps-per-call", type=int, default=800,
                   help="training steps fused into one dispatch via "
                        "lax.scan; amortizes per-call host latency "
                        "(each scanned step is a full real SGD update). "
                        "The default is one dispatch per timed window: "
                        "fewer dispatches measured faster at every size "
                        "and one call removes multi-call wobble from "
                        "the headline")
    p.add_argument("--unroll", type=int, default=5,
                   help="lax.scan unroll factor: >1 lets XLA software-"
                        "pipeline across step boundaries (prefetch next "
                        "step's weights during this step's compute) at "
                        "the cost of code size (measured on ResNet-50 "
                        "bs32: 2 is +4%%, 4-5 are +6%%)")
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="bf16 gradient compression on the wire")
    p.add_argument("--compression", default=None,
                   choices=["none", "bf16", "fp16", "int8", "int8_ef",
                            "fp8"],
                   help="wire-compression policy for the gradient "
                        "collectives (jax/quantize.py): 'int8'/'fp8' are "
                        "block-scaled quantized formats (~4x fewer bytes "
                        "on the wire, scales included); 'int8_ef' adds "
                        "the error-feedback residual (needs "
                        "--sharded-update: the residual rides the "
                        "sharded optimizer state). Overrides "
                        "--fp16-allreduce when given")
    p.add_argument("--sharded-update", action="store_true",
                   help="cross-replica sharded weight update (arxiv "
                        "2004.13336): reduce-scatter the gradient "
                        "buckets, update a 1/N shard of params + "
                        "optimizer state, all-gather the result. Cuts "
                        "per-chip optimizer HBM traffic ~(N-1)/N on a "
                        "multi-chip world; at N=1 it degrades to whole-"
                        "tree packing (a measured NEGATIVE — see "
                        "docs/benchmarks.md 'HBM diet')")
    p.add_argument("--state-dtype", default="f32", choices=["f32", "bf16"],
                   help="resident-state precision policy (HBM diet round "
                        "2): 'bf16' keeps parameters and optimizer state "
                        "in bf16 HBM with the update math in f32; with "
                        "--sharded-update, f32 master weights ride the "
                        "sharded optimizer state as each chip's 1/N "
                        "shard (arxiv 2004.13336 §4) — full-width f32 "
                        "state never touches HBM. Without sharding there "
                        "are no masters (docs/troubleshooting.md on "
                        "bf16 drift)")
    p.add_argument("--remat-blocks", nargs="?", const="act_drop",
                   default=None, choices=["act_drop", "conv_saves"],
                   help="ResNet traffic-removal remat: 'act_drop' "
                        "(default) drops the tagged post-BN/ReLU/join "
                        "activations from the saved set and recomputes "
                        "them in backward from saved conv outputs + BN "
                        "stats; 'conv_saves' saves ONLY conv outputs "
                        "(measured negative — see docs/benchmarks.md). "
                        "Numerics identical either way")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture an XLA profiler trace of one timed "
                        "window into DIR (view: tensorboard --logdir DIR)")
    p.add_argument("--xla-option", action="append", default=[],
                   metavar="KEY=VAL",
                   help="extra XLA compiler option(s) for the step "
                        "executable (repeatable), e.g. "
                        "--xla-option xla_tpu_scoped_vmem_limit_kib=65536")
    p.add_argument("--check", action="store_true",
                   help="perf regression gate (utils/perfwatch): compare "
                        "this run against the newest same-metric "
                        "BENCH_r*.json history record with noise-aware "
                        "bounds from the recorded iteration spread; the "
                        "JSON line gains a \"gate\" object and the exit "
                        "code is nonzero on an img/s drop or "
                        "hbm_gb_per_step creep")
    p.add_argument("--dry", action="store_true",
                   help="parse args and print the one-JSON-line contract "
                        "with null values, without importing jax or "
                        "touching a device — the CI guard "
                        "(tests/test_bench_contract.py) pins that this "
                        "stays import-free and one line")
    args = p.parse_args()

    if args.dry:
        # The exact key set of the real result line below (minus the
        # best-effort "telemetry"/"trace" extras); values null. MUST stay
        # reachable without importing jax/the framework: `bench.py
        # --help` and this guard are how CI proves argparse errors never
        # pay the framework import.
        print(json.dumps({
            "metric": f"{args.model}_train_images_per_sec_per_chip"
                      f"_bs{args.batch_size}",
            "value": None, "unit": "images/sec/chip", "vs_baseline": None,
            "step_time_ms": None, "gflops_per_step": None, "mfu": None,
            "hbm_gb_per_step": None, "hbm_source": None,
            "membw_util": None, "spread_pct": None, "gate": None,
            "state_dtype": None, "compression": None, "numerics": None,
            "dry": True,
        }))
        return

    # Numerics observatory (core/numerics.py): default the in-step
    # gradient-health policy OFF for the bench — the headline hot loop
    # must compile to the identical HLO as the recorded BENCH_r* history
    # (the off-policy pin in tests/test_numerics.py). setdefault: an
    # operator explicitly exporting HVD_NUMERICS=warn|halt gets an
    # instrumented (and honestly slower) run.
    os.environ.setdefault("HVD_NUMERICS", "off")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax
    from horovod_tpu import models
    # Deliberately imported here, not at module top: `bench.py --help`
    # and argparse errors must not pay the framework+jax import.
    from horovod_tpu.utils import hardware as hw

    hvd.init()
    nchips = hvd.size()

    model_kw = {"stem": args.stem} if args.stem else {}
    model = models.get_model(args.model, **model_kw)
    # --compression (the quantized-collectives subsystem) wins over the
    # legacy --fp16-allreduce spelling; argparse already vetted the
    # name, resolve() threads the policy object through.
    compression_name = (args.compression
                        or ("fp16" if args.fp16_allreduce else "none"))
    compression = hvd_jax.Compression.resolve(compression_name)
    # fused_update: the ~160 per-parameter update fusions collapse into
    # per-dtype flat buffers (horovod_tpu/jax/fused.py) — profiling shows
    # per-tensor updates + their HBM<->VMEM copies costing ~2.5 ms of an
    # 11.4 ms step at bs32.
    # state_dtype (HBM diet round 2): resident params + optimizer state
    # in bf16 HBM, update math in f32; with --sharded-update the f32
    # masters ride the sharded state as 1/N shards.
    state_dtype = None if args.state_dtype == "f32" else args.state_dtype
    opt = hvd_jax.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression,
        fused_update=True, sharded_update=args.sharded_update,
        state_dtype=state_dtype)

    rng = jax.random.PRNGKey(0)
    # bf16 host feed: the model computes in bf16; feeding bf16 halves the
    # host->device bytes and skips the on-device upcast-downcast.
    images_host = np.random.uniform(
        size=(args.batch_size, args.image_size, args.image_size, 3)
    ).astype(jnp.bfloat16)

    variables = model.init(rng, jnp.asarray(images_host), False)
    # Label range from the model's own head width: a hardcoded 1000
    # NaNs the loss for the 10-class mnist_* models.
    labels_host = np.random.randint(0, model.num_classes,
                                    size=(args.batch_size,))
    params, batch_stats = variables["params"], variables.get("batch_stats", {})
    # Resident params at the policy width (identity under f32; the
    # masters — when sharded — derive from these in opt.init, so cast
    # FIRST). BN statistics stay f32: running moments accumulate badly
    # in bf16.
    params = hvd_jax.cast_resident_params(params, state_dtype)
    opt_state = opt.init(params)
    # Startup sync, as every reference example does before training
    # (reference: BroadcastGlobalVariablesHook).
    params = hvd_jax.broadcast_parameters(params, root_rank=0)

    def loss_fn(params, batch_stats, images, labels, dropout_rng):
        # Unused rng collections are ignored by models without dropout
        # (resnet/mnist); vgg16/inceptionv3 train with 0.5 dropout and
        # need it — a benchmark that silently disabled dropout would
        # overstate them.
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images, True,
            mutable=["batch_stats"], rngs={"dropout": dropout_rng})
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, mutated["batch_stats"]

    if args.remat_blocks:
        from horovod_tpu.models import resnet as _resnet

        # Traffic-removal remat (see models/resnet.py policy docstrings).
        policy = (_resnet.act_drop_policy() if args.remat_blocks == "act_drop"
                  else _resnet.conv_saves_policy())
        loss_fn = jax.checkpoint(loss_fn, policy=policy)

    def one_step(params, batch_stats, opt_state, key, images, labels):
        key, sub = jax.random.split(key)
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels,
                                   sub)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_bs, opt_state, key, hvd_jax.allreduce(loss)

    spc = max(1, args.steps_per_call)

    # Sharded update: each chip carries only its 1/N block of the
    # momentum/param flat buffers, so the optimizer state rides the mesh
    # as P('hvd') instead of replicated.
    ospec = (hvd_jax.sharded_state_specs(opt_state)
             if args.sharded_update else P())

    @hvd_jax.jit(
        in_specs=(P(), P(), ospec, P(),
                  P(hvd_jax.HVD_AXIS), P(hvd_jax.HVD_AXIS)),
        out_specs=(P(), P(), ospec, P(), P()),
        donate_argnums=(0, 1, 2),
    )
    def train_step(params, batch_stats, opt_state, key, images, labels):
        if spc == 1:
            return one_step(params, batch_stats, opt_state, key, images,
                            labels)

        def body(carry, _):
            params, batch_stats, opt_state, key = carry
            params, batch_stats, opt_state, key, loss = one_step(
                params, batch_stats, opt_state, key, images, labels)
            return (params, batch_stats, opt_state, key), loss

        (params, batch_stats, opt_state, key), losses = jax.lax.scan(
            body, (params, batch_stats, opt_state, key), None, length=spc,
            unroll=max(1, args.unroll))
        return params, batch_stats, opt_state, key, losses[-1]

    # Each chip sees the full per-chip batch: global batch = B * size.
    mesh = hvd.mesh()
    from jax.sharding import NamedSharding

    def chip_batch(x):
        shards = [jax.device_put(x, d) for d in jax.local_devices()
                  if d in mesh.devices.flat]
        global_shape = (x.shape[0] * nchips,) + x.shape[1:]
        return jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, P(hvd_jax.HVD_AXIS)), shards)

    images = chip_batch(images_host)
    labels = chip_batch(labels_host)
    step_key = jax.random.PRNGKey(hvd.rank())  # dropout stream (vgg/inception)

    # XLA's own FLOP count for the compiled step (reference methodology
    # anchor: tensorflow_synthetic_benchmark.py:96-106 reports img/sec; we
    # additionally pin it to hardware truth).
    # NB: XLA:TPU cost analysis counts a while-loop (lax.scan) body ONCE,
    # so for any steps-per-call this is the per-STEP figure (verified on
    # chip: spc=1 and spc=10 both report 765.2 GFLOP for ResNet-50 bs32).
    # The AOT executable is reused for the run itself — the traced-call jit
    # cache is separate, so falling back to train_step() would compile the
    # same program a second time.
    step_fn = train_step
    flops_per_step = 0.0
    counted = 1  # scan steps cost_analysis holds (set with flops below)
    bytes_per_step = None  # None = unavailable (cost analysis failed
    # or the body is unrolled — see below); never a fake measured zero.
    copts = {}
    for kv in args.xla_option:
        if "=" not in kv:
            p.error(f"--xla-option expects KEY=VAL, got {kv!r}")
        k, v = kv.split("=", 1)
        copts[k] = v
    try:
        compiled = train_step.lower(
            params, batch_stats, opt_state, step_key, images,
            labels).compile(compiler_options=copts or None)
        step_fn = compiled
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        # The scan BODY is counted once (verified on chip, note above);
        # unrolling multiplies the steps it holds (verified on chip:
        # unroll=4, spc=50 reports exactly 6x the one-step FLOPs —
        # 4-step body + 2-step peeled remainder).
        unroll = max(1, args.unroll) if spc > 1 else 1
        counted = hw.scan_cost_analysis_steps(spc, args.unroll)
        flops_per_step = float(ca.get("flops", 0.0)) / counted
        # "bytes accessed" does NOT follow the same rule under unrolling
        # (observed 0.66 GB/step at unroll=2 vs 16.95 at unroll=1 for the
        # same program) — only trust it on the un-unrolled body; report
        # null otherwise (0.0 would read as a measured zero).
        bytes_per_step = (float(ca.get("bytes accessed", 0.0))
                          if unroll == 1 else None)
    except Exception as e:  # pragma: no cover - cost analysis is best-effort
        if copts:
            # Silently benchmarking WITHOUT the requested compiler options
            # would attribute a default-config number to the flag; fail
            # loudly instead.
            print(f"# compile with --xla-option {copts} failed: {e}",
                  file=sys.stderr)
            raise
        print(f"# cost_analysis unavailable: {e}", file=sys.stderr)

    def run_batches(ncalls):
        nonlocal params, batch_stats, opt_state, step_key
        loss = None
        for _ in range(ncalls):
            params, batch_stats, opt_state, step_key, loss = step_fn(
                params, batch_stats, opt_state, step_key, images, labels)
        # Real device->host fetch: the only reliable execution barrier on
        # the tunneled platform (block_until_ready returns early there).
        return float(np.asarray(loss))

    ncalls_warm = max(1, args.num_warmup_batches // spc)
    if ncalls_warm * spc != args.num_warmup_batches:
        print(f"# note: warmup rounded to {ncalls_warm * spc} batches "
              f"(multiple of --steps-per-call {spc})", file=sys.stderr)
    ncalls_iter = max(1, args.num_batches_per_iter // spc)
    batches_per_iter = ncalls_iter * spc
    if batches_per_iter != args.num_batches_per_iter:
        print(f"# note: window rounded to {batches_per_iter} batches "
              f"(multiple of --steps-per-call {spc})", file=sys.stderr)

    loss = run_batches(ncalls_warm)
    assert np.isfinite(loss), f"diverged in warmup: {loss}"

    # One profiled window ALWAYS runs (into --profile DIR when given,
    # else a tempdir): the capture is where the measured HBM-traffic
    # fields of the JSON line come from (docs/benchmarks.md "The
    # ceiling, measured") — async-DMA payload + fusion direct streams,
    # not XLA's bytes-accessed estimate.
    measured_gb_per_step = None

    def _measure_from_profile(prof_dir, new_files):
        from horovod_tpu.utils import xplane

        # Only THIS run's capture: a reused --profile dir still holds
        # earlier xplane files, which would double every byte count.
        spaces = xplane._load_spaces(prof_dir, files=new_files)
        dma = xplane.dma_bytes(prof_dir, spaces=spaces)
        direct = xplane.fusion_direct_bytes(prof_dir, spaces=spaces)
        window_steps = ncalls_iter * spc
        if dma["bytes"] or direct:
            return (dma["bytes"] + direct) / 1e9 / window_steps
        return None

    if args.profile:
        # User-requested capture: failures stay LOUD (a silent missing
        # trace is worse than a crashed bench); only the derived HBM
        # numbers are best-effort.
        from horovod_tpu.utils import profiler

        before = set(profiler.trace_files(args.profile))
        with profiler.profile(args.profile):
            run_batches(ncalls_iter)
        new_files = [f for f in profiler.trace_files(args.profile)
                     if f not in before]
        if not new_files:
            # A capture that lands nothing is a broken measurement, not
            # a degraded one: every derived HBM figure would silently
            # read as "no traffic". Fail loudly (profiler.capture raises
            # the same way).
            print(f"# ERROR: --profile {args.profile} produced no "
                  "*.xplane.pb (is another trace active? is the "
                  "profiler plugin available?)", file=sys.stderr)
            raise SystemExit(3)
        print(f"# profile: {len(new_files)} new xplane file(s) in "
              f"{args.profile}", file=sys.stderr)
        try:
            measured_gb_per_step = _measure_from_profile(args.profile,
                                                         new_files)
        except Exception as e:  # pragma: no cover - analysis best-effort
            print(f"# profile-based HBM measurement unavailable: {e}",
                  file=sys.stderr)
    else:
        # Implicit capture into a tempdir purely for the measured HBM
        # fields: fully best-effort, must never fail the bench.
        try:
            import tempfile

            from horovod_tpu.utils import profiler

            with tempfile.TemporaryDirectory(prefix="bench_prof_") as td:
                with profiler.profile(td):
                    run_batches(ncalls_iter)
                measured_gb_per_step = _measure_from_profile(
                    td, profiler.trace_files(td))
        except Exception as e:  # pragma: no cover - measurement best-effort
            print(f"# profile-based HBM measurement unavailable: {e}",
                  file=sys.stderr)

    rates = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        run_batches(ncalls_iter)
        dt = time.perf_counter() - t0
        rates.append(args.batch_size * batches_per_iter / dt)

    per_chip = float(np.median(rates))
    step_time = args.batch_size / per_chip
    peak = hw.peak_flops(jax.devices()[0])
    peak_bw = hw.peak_hbm_bw(jax.devices()[0])
    if peak and flops_per_step / step_time > peak:
        # Guard against a cost-analysis that counted the full scan (all
        # spc steps, would make MFU read > 1 on a sane measurement): the
        # value was already divided by `counted`, so recover one step's
        # FLOPs as raw/spc = flops_per_step * counted / spc.
        flops_per_step *= counted / spc
        print("# note: cost_analysis FLOPs exceeded chip peak; assuming it "
              f"counted all {spc} scan steps and rescaling", file=sys.stderr)
    if (bytes_per_step and peak_bw
            and bytes_per_step / step_time > 2 * peak_bw):
        bytes_per_step /= spc  # same scan-body pitfall as FLOPs
        print("# note: cost_analysis bytes exceeded 2x chip HBM peak; "
              f"assuming scan body counted {spc}x and dividing",
              file=sys.stderr)
    mfu = (flops_per_step / step_time / peak
           ) if peak and flops_per_step else None
    # Preferred: the MEASURED per-step HBM traffic from the profiled
    # window (async-DMA payload + fusion direct streams — see
    # docs/benchmarks.md "The ceiling, measured"). Fallback: XLA's
    # "bytes accessed", which counts each op's operands+results and so
    # over-states true HBM traffic (measured discount ~0.46); the
    # hbm_source field says which one the line carries. MFU + a high
    # membw_util together locate the step on the roofline.
    if measured_gb_per_step is not None:
        hbm_bytes_step = measured_gb_per_step * 1e9
        hbm_source = "measured"
    else:
        hbm_bytes_step = bytes_per_step
        hbm_source = "cost_analysis" if bytes_per_step is not None else None
    membw = (hbm_bytes_step / step_time / peak_bw
             ) if peak_bw and hbm_bytes_step else None
    result = {
        "metric": f"{args.model}_train_images_per_sec_per_chip"
                  f"_bs{args.batch_size}",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_DEVICE, 3),
        "step_time_ms": round(step_time * 1e3, 3),
        # None (not 0.0) when cost analysis failed — same no-fake-zero
        # rule as hbm_gb_per_step.
        "gflops_per_step": (round(flops_per_step / 1e9, 1)
                            if flops_per_step else None),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "hbm_gb_per_step": (round(hbm_bytes_step / 1e9, 2)
                            if hbm_bytes_step is not None else None),
        "hbm_source": hbm_source,
        "membw_util": round(membw, 3) if membw is not None else None,
        # Iteration spread as a percentage of the median — the noise
        # bound the perfwatch gate derives its pass/fail margin from.
        "spread_pct": round((max(rates) - min(rates)) / per_chip * 100, 2)
        if per_chip else None,
        "gate": None,  # filled by --check below; present-but-null else
        "state_dtype": args.state_dtype,
        "compression": compression_name,
        "numerics": None,  # filled post-window below; null under --dry
    }
    # Numerics summary (core/numerics.py): policy + anything the run
    # observed (eager-path health, verdicts, consistency). Collected
    # AFTER the timed windows like telemetry; with the default bench
    # policy (off) it reports {"policy": "off", ...nulls} — the honest
    # "nothing was watched" record.
    try:
        from horovod_tpu.core import numerics as _numerics

        result["numerics"] = _numerics.compact()
    except Exception as e:  # pragma: no cover - never fail the bench
        print(f"# numerics summary unavailable: {e}", file=sys.stderr)
    # Unified telemetry (core/telemetry.py): eager-collective counts, the
    # startup broadcast, engine activity if any — read AFTER the timed
    # windows so collecting it can never perturb the headline. The hot
    # path itself is the AOT executable, which carries no instrumentation.
    try:
        from horovod_tpu.core import telemetry as _telemetry

        result["telemetry"] = _telemetry.compact()
    except Exception as e:  # pragma: no cover - never fail the bench line
        print(f"# telemetry unavailable: {e}", file=sys.stderr)
    # Distributed tracing: with HVD_TIMELINE set, report the merged
    # per-rank trace path. Collected POST-window (the AOT hot path
    # carries no timeline instrumentation — only the engines' host-side
    # spans land in it), and strictly best-effort.
    import os as _os

    tl_env = (_os.environ.get("HVD_TIMELINE")
              or _os.environ.get("HOROVOD_TIMELINE"))
    if tl_env:
        try:
            from horovod_tpu.core import engine as _eng

            if _eng._engine is not None:
                _eng.shutdown_engine()  # close per-rank files for merge
            from horovod_tpu.core import timeline as _tl

            if _tl.is_dir_mode(tl_env):
                from horovod_tpu.utils import trace as _trace

                result["trace"] = _trace.merge(tl_env)["path"]
            elif _os.path.exists(tl_env):
                result["trace"] = tl_env  # single-file spelling
        except Exception as e:  # pragma: no cover - never fail the bench
            print(f"# trace merge unavailable: {e}", file=sys.stderr)
    gate_failed = False
    if args.check:
        # Regression gate (ROADMAP item 2: img/s and HBM traffic must
        # not silently creep back). perfwatch is stdlib-only; the
        # history lives next to this script (BENCH_r*.json). Guarded:
        # whatever the gate does, the one-JSON-line contract holds — a
        # gating error is reported as status "error" on stderr, never a
        # traceback that eats the measured run.
        try:
            from horovod_tpu.utils import perfwatch as _pw

            repo = _os.path.dirname(_os.path.abspath(__file__))
            # The noise bound comes from result["spread_pct"] — ONE
            # definition of the iteration spread for both the JSON line
            # and the gate.
            cur = _pw.record_from_bench(result)
            gate = _pw.gate(cur, _pw.pick_reference(
                _pw.load_history(repo), cur))
            result["gate"] = gate
            gate_failed = gate["status"] == "fail"
            print("# " + _pw.gate_line(gate), file=sys.stderr)
        except Exception as e:  # pragma: no cover - defensive
            result["gate"] = {"status": "error", "note": str(e)[:300]}
            print(f"# perfwatch: gate errored: {e}", file=sys.stderr)
    print(json.dumps(result))
    print(f"# {nchips} chip(s), spread {min(rates):.0f}-{max(rates):.0f} "
          f"img/sec over {args.num_iters} iters, "
          f"platform={jax.devices()[0].platform} "
          f"({jax.devices()[0].device_kind})", file=sys.stderr)
    if gate_failed:
        # The one JSON line above already carries the verdict; the
        # nonzero exit is what CI keys on (docs/benchmarks.md
        # "Regression gate").
        raise SystemExit(4)


if __name__ == "__main__":
    main()
