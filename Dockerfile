# horovod_tpu — TPU-VM image (the role of the reference's Dockerfile:
# a ready-to-run training image with the framework, frontends and
# examples baked in; reference: Dockerfile:1-84, build-docker-images.sh).
#
# The reference's image stacks CUDA + NCCL + MPI + framework wheels.
# On TPU the stack is radically simpler: libtpu ships inside the
# `jax[tpu]` wheel, the data plane is XLA, and the launcher replaces
# mpirun — so this is a slim python image, not an nvidia base.
#
# Build:   ./build-image.sh   (or: docker build -t horovod-tpu .)
# Run on a Cloud TPU VM (one worker per host, all hosts of a pod slice):
#   docker run --privileged --network host horovod-tpu \
#       python examples/jax_mnist.py --synthetic
# `--privileged --network host` grants the container the TPU device
# nodes (/dev/accel*) and the host networking the ICI/DCN mesh uses —
# the TPU analogue of the reference's --gpus/--network flags
# (docs/docker.md). See docs/deploy.md for pod-slice orchestration.

FROM python:3.12-slim AS build

# Native toolchain for the C++ engine (core/native/hvdcore.cc). The
# runtime stage copies the built artifacts and drops the compilers.
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY . .
RUN pip install --no-cache-dir build && python -m build --wheel

FROM python:3.12-slim

# jax[tpu] carries libtpu; torch stays CPU (it is a frontend here, the
# chips belong to XLA — docs/concepts.md "Differences from Horovod").
RUN pip install --no-cache-dir \
        "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
        flax optax tensorflow-cpu && \
    pip install --no-cache-dir torch --index-url https://download.pytorch.org/whl/cpu

COPY --from=build /src/dist/*.whl /tmp/
RUN pip install --no-cache-dir /tmp/*.whl && rm /tmp/*.whl

# Examples ship in the image like the reference's (they are the
# de-facto integration tier and double as smoke tests on a fresh VM).
COPY examples /workspace/examples
COPY docs /workspace/docs
WORKDIR /workspace

# Engine knobs documented in docs/running.md; defaults match source.
ENV HVD_ENGINE=native

CMD ["python", "-c", "import horovod_tpu as hvd; hvd.init(); print(f'horovod_tpu OK: {hvd.size()} chip(s)')"]
