"""TF collective ops with registered gradients (reference:
horovod/tensorflow/mpi_ops.py + the custom-op kernels of
tensorflow/mpi_ops.cc).

The reference implements TF custom C++ ops that enqueue into the engine.
Here the bridge is ``tf.py_function`` into the XLA data plane: TF runs on
host CPU (there is no TF-on-TPU in this stack — JAX owns the chips), so
collectives hop tensor → numpy → mesh collective → numpy → tensor, exactly
the staging shape of the reference's CudaOnCPU path
(torch/mpi_ops_v2.cc:78-110). Gradients are registered per the reference:
allreduce→allreduce (mpi_ops.py:94-105), allgather→allreduce+slice
(:127-148), broadcast→allreduce zeroed off-root (:168-183).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from horovod_tpu.common import topology as _topo
from horovod_tpu.ops import collectives as _C


def _np_collective(kind: str, t: np.ndarray, *, average=False, root=0):
    import jax.numpy as jnp

    x = jnp.asarray(t)
    if kind == "allreduce":
        out = _C.allreduce(x, average=average)
    elif kind == "allgather":
        out = _C.allgather(x)
    elif kind == "broadcast":
        out = _C.broadcast(x, root)
    else:
        raise ValueError(kind)
    return np.asarray(out)


def _bridge(kind: str, tensor: tf.Tensor, **kw) -> tf.Tensor:
    """Run an XLA-mesh collective on a TF tensor via py_function so the op
    works in both eager and tf.function graphs."""

    def fn(t):
        return _np_collective(kind, t.numpy(), **kw)

    out = tf.py_function(fn, [tensor], Tout=tensor.dtype)
    if kind != "allgather":
        out.set_shape(tensor.shape)
    else:
        shape = tensor.shape.as_list()
        if shape and shape[0] is not None:
            shape[0] = shape[0] * _topo.size()
        out.set_shape(shape)
    return out


def size() -> int:
    return _topo.size()


def rank() -> int:
    return _topo.rank()


def _allreduce(tensor: tf.Tensor, average: bool = False,
               name: Optional[str] = None) -> tf.Tensor:
    @tf.custom_gradient
    def op(x):
        y = _bridge("allreduce", x, average=average)

        def grad(dy):
            # Reference: allreduce's gradient is an allreduce
            # (tensorflow/mpi_ops.py:94-105).
            return _bridge("allreduce", dy, average=average)

        return y, grad

    return op(tensor)


def allgather(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    """Concat along dim 0 over ranks (reference: mpi_ops.py:108-126)."""
    n = _topo.size()

    @tf.custom_gradient
    def op(x):
        y = _bridge("allgather", x)

        def grad(dy):
            # Reference: allreduce(SUM) then slice this rank's rows
            # (mpi_ops.py:127-148). Equal first dims per rank here (the
            # single-controller case); the eager varying-dim path exists
            # on the jax frontend.
            summed = _bridge("allreduce", dy, average=False)
            per = tf.shape(summed)[0] // n
            r = _topo.rank()
            return summed[per * r: per * (r + 1)]

        return y, grad

    return op(tensor)


def broadcast(tensor: tf.Tensor, root_rank: int,
              name: Optional[str] = None) -> tf.Tensor:
    """Every rank receives root's value (reference: mpi_ops.py:151-183)."""
    root_rank = _C._check_root(root_rank)

    @tf.custom_gradient
    def op(x):
        y = _bridge("broadcast", x, root=root_rank)

        def grad(dy):
            # Reference: reduce to root, zero elsewhere (mpi_ops.py:
            # 168-183).
            g = _bridge("allreduce", dy, average=False)
            if _topo.rank() == root_rank:
                return g
            return tf.zeros_like(g)

        return y, grad

    return op(tensor)
