"""TF collective ops with registered gradients (reference:
horovod/tensorflow/mpi_ops.py + the custom-op kernels of
tensorflow/mpi_ops.cc).

The reference implements TF custom C++ ops that enqueue into the engine.
Here the bridge is ``tf.py_function`` into the XLA data plane: TF runs on
host CPU (there is no TF-on-TPU in this stack — JAX owns the chips), so
collectives hop tensor → numpy → mesh collective → numpy → tensor, exactly
the staging shape of the reference's CudaOnCPU path
(torch/mpi_ops_v2.cc:78-110). Gradients are registered per the reference:
allreduce→allreduce (mpi_ops.py:94-105), allgather→allreduce+slice
(:127-148), broadcast→allreduce zeroed off-root (:168-183).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from horovod_tpu.common import topology as _topo
from horovod_tpu.ops import collectives as _C


def _np_collective(kind: str, t: np.ndarray, *, name: str,
                   average=False, root=0, wire=None, priority=None):
    """Execute through the ENGINE, not the eager compiled collectives.

    TF's graph executor runs independent py_function nodes concurrently
    and in no fixed order, so two controllers (or two executor threads
    in one process) would issue eager mesh programs in different orders
    — observed as a gloo size-mismatch abort / cross-module rendezvous
    deadlock in the estimator example's 6-gradient graph. Unordered
    multi-controller submission is exactly what the engine's negotiation
    protocol exists for (the reference's TF kernels likewise enqueue
    into its engine: tensorflow/mpi_ops.cc EnqueueTensorAllreduce);
    requests match across controllers by ``name``. Bonus: concurrently
    blocked py_functions land in one engine cycle and fuse (C5)."""
    from horovod_tpu.core import engine as _eng

    e = _eng.get_engine()
    # donate=True: the buffer is a py_function-scoped temporary (TF hands
    # the body its own eager tensor, alive in this frame until the
    # synchronize below returns, i.e. past completion), so the engine
    # can reference it in place instead of snapshotting — the engine
    # only READS donated buffers; results land in its pooled buffers.
    if kind == "allreduce":
        # The engine wire format is >=1-d; restore scalar shape after.
        # `wire` is the per-request engine wire policy ('int8'/'fp8');
        # `priority` the serving-plane scheduling class.
        h = e.allreduce_async(name, np.atleast_1d(t), average,
                              compression=wire, donate=True,
                              priority=priority)
        return e.synchronize(h).reshape(np.shape(t))
    if kind == "allgather":
        # Scalars ride the >=1-d wire as one gathered row apiece.
        return e.synchronize(e.allgather_async(name, np.atleast_1d(t),
                                               donate=True,
                                               priority=priority))
    if kind == "broadcast":
        h = e.broadcast_async(name, np.atleast_1d(t), root, donate=True,
                              priority=priority)
        return e.synchronize(h).reshape(np.shape(t))
    raise ValueError(kind)


def _seq_next(key: str) -> int:
    """Per-kind sequence number scoped to the GRAPH under construction.

    Engine names must match across processes; they are assigned at
    op-construction time, so they must depend only on the op's position
    within the program being built — never on how many programs were
    built before. A process-global counter broke exactly there (r4
    advisor): one process retracing a tf.function (new input shape,
    rank-conditional branch) marched its counter past its peers' and
    every later collective stalled on mismatched names until timeout.
    Scoping the counter to the graph makes a re-trace rebuild the SAME
    names. Eager ops scope to the persistent default graph — one
    process-wide sequence, matched across processes by identical call
    order (the contract the reference's stable tensor names rely on).

    Same-name reuse across graphs/steps is safe: the engine pairs
    same-name requests FIFO per process, the per-step reuse pattern the
    reference is built on (tensor names recur every iteration)."""
    g = tf.compat.v1.get_default_graph()
    d = getattr(g, "_hvd_bridge_seq", None)
    if d is None:
        d = {}
        g._hvd_bridge_seq = d
    seq = d.get(key, 0)
    d[key] = seq + 1
    return seq


def _bridge_group(kind: str, tensors, names, *, average=False, root=0,
                  wires=None, priority=None):
    """Run N same-kind collectives through ONE py_function, submitting
    every engine request before waiting on any.

    TF executes py_function bodies strictly sequentially per process
    (measured: 4 sleeping py_functions in one session.run never overlap),
    in a schedule order that differs across processes — so N blocking
    single-tensor bridges in one graph can wedge as rank A inside op X
    while rank B sits inside op Y, a cycle no negotiation can resolve
    (observed: the estimator example's variable broadcast, stalled
    ".5"/".6" on the two ranks). Submitting the whole group first makes
    every member visible to the engine regardless of executor order —
    the property the reference's ASYNC TF kernels have natively
    (tensorflow/mpi_ops.cc enqueues and returns) — and lands the group
    in one engine cycle, where it fuses (C5).
    """
    tensors = list(tensors)
    names = list(names)
    kinds = [kind] * len(tensors) if isinstance(kind, str) else list(kind)
    # Per-member engine wire policy ('int8'/'fp8'/None), aligned with
    # `tensors` — the per-tensor Compression overrides ride here.
    wires = list(wires) if wires is not None else [None] * len(tensors)

    def fn(*ts):
        from horovod_tpu.core import engine as _eng

        e = _eng.get_engine()
        handles = []
        # donate=True: each buffer lives in this frame (ts) until every
        # synchronize below returned — past completion — so the engine
        # may reference it in place and skip the submit snapshot (it
        # only READS donated buffers).
        # The group partitions into consecutive same-kind runs, and each
        # run rides ONE batched engine call (Engine.submit_n /
        # hvd_engine_enqueue_n): one GIL crossing and one engine wakeup
        # for a whole gradient bucket instead of per-tensor submits.
        # Submit-all-then-wait inside this one py_function is preserved
        # exactly (the tf-bridge-group deadlock rule).
        members = list(zip(kinds, names, ts, wires))
        i = 0
        while i < len(members):
            k = members[i][0]
            if k not in ("allreduce", "broadcast", "allgather"):
                raise ValueError(k)
            j = i
            while j < len(members) and members[j][0] == k:
                j += 1
            run = members[i:j]
            i = j
            if len(run) > 1:
                reqs = [_eng.SubmitRequest(
                            name, np.atleast_1d(np.asarray(t.numpy())),
                            average=average, root_rank=root,
                            compression=w, donate=True,
                            priority=priority)
                        for _, name, t, w in run]
                handles.extend(e.submit_n(k, reqs))
                continue
            _, name, t, w = run[0]
            a = np.atleast_1d(np.asarray(t.numpy()))
            if k == "allreduce":
                handles.append(e.allreduce_async(name, a, average,
                                                 compression=w,
                                                 donate=True,
                                                 priority=priority))
            elif k == "broadcast":
                handles.append(e.broadcast_async(name, a, root,
                                                 donate=True,
                                                 priority=priority))
            else:
                handles.append(e.allgather_async(name, a, donate=True,
                                                 priority=priority))
        # Drain EVERY handle even when one errors (then re-raise the
        # first failure): an abandoned handle would orphan its donated
        # buffer's pin on the native engine, and the group's remaining
        # collectives must complete cross-rank regardless.
        outs, first_err = [], None
        for h in handles:
            try:
                outs.append(e.synchronize(h))
            except Exception as exc:
                if first_err is None:
                    first_err = exc
                outs.append(None)
        if first_err is not None:
            raise first_err
        # allgather legitimately changes the first dim; everything else
        # restores the submitted shape (scalars ride the >=1-d wire).
        return [o if k == "allgather" else o.reshape(np.shape(t))
                for k, o, t in zip(kinds, outs, ts)]

    outs = tf.py_function(fn, tensors, Tout=[t.dtype for t in tensors])
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    for k, o, t in zip(kinds, outs, tensors):
        if k == "allgather":
            shape = t.shape.as_list() if t.shape.rank is not None else None
            if shape:
                shape[0] = None  # per-rank first dims may differ
            o.set_shape(shape)
        else:
            o.set_shape(t.shape)
    return list(outs)


def _group_names(kind: str, labels) -> list:
    """Stable engine names for a grouped collective: a per-kind,
    per-graph sequence number (identical across processes — every
    controller constructs the same program in the same order) plus a
    per-member label (variable name), so request matching survives
    arbitrary EXECUTION order and asymmetric re-traces."""
    seq = _seq_next("g" + kind)
    return [f"tf.{kind}g{seq}.{label}" for label in labels]


def _bridge(kind: str, tensor: tf.Tensor, name: Optional[str] = None,
            **kw) -> tf.Tensor:
    """Run an engine collective on a TF tensor via py_function so the op
    works in both eager and tf.function graphs.

    The engine name is assigned at op-CONSTRUCTION time — from the
    user-supplied ``name`` when given (fully retrace-proof, the
    reference's contract), else a per-kind per-graph counter: every
    controller builds the same program in the same order, so node N gets
    the same name everywhere — the negotiation key the engine matches
    requests by — while concurrent EXECUTION order stays free.

    NOTE (v1 Session graphs): py_function bodies execute strictly
    sequentially per process; tf.function and eager run them in program
    order (auto control deps serialize stateful ops), but a v1 Session
    schedules them in arbitrary order, so MULTIPLE independent blocking
    single-op collectives in one session.run can wedge cross-rank. The
    v1 surfaces this package ships (hooks, DistributedOptimizer,
    broadcast_global_variables) group their collectives through ONE
    py_function (_bridge_group); hand-built v1 graphs with several
    public per-tensor ops should do the same."""
    # 'u.' keeps user names out of the auto-counter namespace (a user
    # name of '0' must not pair with an unnamed op's 'tf.{kind}.0').
    opname = (f"tf.{kind}.u.{name}" if name
              else f"tf.{kind}.{_seq_next(kind)}")

    def fn(t):
        return _np_collective(kind, t.numpy(), name=opname, **kw)

    out = tf.py_function(fn, [tensor], Tout=tensor.dtype)
    if kind != "allgather":
        out.set_shape(tensor.shape)
    else:
        # Per-rank first dims may differ (reference: mpi_ops.py:108-126),
        # so the gathered first dim is dynamic; a scalar input contributes
        # one row on the >=1-d wire.
        shape = (tensor.shape.as_list()
                 if tensor.shape.rank is not None else None)
        if shape is not None:
            shape = [None] + shape[1:] if shape else [None]
        out.set_shape(shape)
    return out


def size() -> int:
    return _topo.size()


def rank() -> int:
    return _topo.rank()


def _allreduce(tensor: tf.Tensor, average: bool = False,
               name: Optional[str] = None, wire=None,
               priority=None) -> tf.Tensor:
    @tf.custom_gradient
    def op(x):
        y = _bridge("allreduce", x, name=name, average=average, wire=wire,
                    priority=priority)

        def grad(dy):
            # Reference: allreduce's gradient is an allreduce
            # (tensorflow/mpi_ops.py:94-105).
            gname = f"{name}.grad" if name else None
            return _bridge("allreduce", dy, name=gname, average=average,
                           wire=wire, priority=priority)

        return y, grad

    return op(tensor)


def allgather(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    """Concat along dim 0 over ranks (reference: mpi_ops.py:108-126)."""

    @tf.custom_gradient
    def op(x):
        y = _bridge("allgather", x, name=name)
        in_rank = x.shape.rank

        def grad(dy):
            # Reference: allreduce(SUM) the cotangent, then slice this
            # rank's rows by the TRUE per-rank first dims — ranks may
            # contribute unequal counts, so the sizes are themselves
            # allgathered (mpi_ops.py:127-148; torch does the same,
            # torch/mpi_ops.py:169-176). Both collectives ride ONE
            # grouped py_function: two blocking single-op bridges could
            # wedge cross-rank under TF's sequential executor.
            gname = f"{name}.grad" if name else None
            if in_rank == 0:
                # Every rank contributes exactly one row by construction:
                # no dims exchange needed.
                summed = _bridge("allreduce", dy, name=gname, average=False)
                r = _topo.rank()
                return tf.reshape(summed[r:r + 1], [])
            # [first_dim]; yields [1] for a runtime scalar (unknown static
            # rank) riding the >=1-d wire.
            my_dim = tf.concat([tf.shape(x), [1]], 0)[:1]
            names = ([f"tf.agradg.{gname}.sum", f"tf.agradg.{gname}.dims"]
                     if gname else _group_names("agrad", ["sum", "dims"]))
            summed, dims = _bridge_group(
                ["allreduce", "allgather"], [dy, my_dim], names)
            r = _topo.rank()
            offset = tf.reduce_sum(dims[:r])
            begin = tf.concat(
                [[offset], tf.zeros([tf.rank(summed) - 1], tf.int32)], 0)
            size_vec = tf.concat([my_dim, tf.shape(summed)[1:]], 0)
            sliced = tf.slice(summed, begin, size_vec)
            return tf.reshape(sliced, tf.shape(x))

        return y, grad

    return op(tensor)


def broadcast(tensor: tf.Tensor, root_rank: int,
              name: Optional[str] = None) -> tf.Tensor:
    """Every rank receives root's value (reference: mpi_ops.py:151-183)."""
    root_rank = _C._check_root(root_rank)

    @tf.custom_gradient
    def op(x):
        y = _bridge("broadcast", x, name=name, root=root_rank)

        def grad(dy):
            # Reference: reduce to root, zero elsewhere (mpi_ops.py:
            # 168-183).
            gname = f"{name}.grad" if name else None
            g = _bridge("allreduce", dy, name=gname, average=False)
            if _topo.rank() == root_rank:
                return g
            return tf.zeros_like(g)

        return y, grad

    return op(tensor)
