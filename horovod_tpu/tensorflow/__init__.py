"""TensorFlow frontend (reference: horovod/tensorflow/__init__.py).

TF computes on host CPU in this stack (the chips belong to JAX/XLA);
collectives stage through the mesh like the reference's CudaOnCPU path.
For TPU-resident TF-free training use :mod:`horovod_tpu.jax` — this
frontend exists so reference TF scripts port mechanically.
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from horovod_tpu.common.topology import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    cross_size,
    cross_rank,
    mpi_threads_supported,
)
from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401
    _allreduce,
    allgather,
    broadcast,
)


def allreduce(tensor, average: bool = True, device_dense: str = "",
              device_sparse: str = "", compression=Compression.none):
    """Allreduce with the reference's sparse path: IndexedSlices become an
    allgather of values+indices (reference:
    horovod/tensorflow/__init__.py:48-94)."""
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values)
        indices = allgather(tensor.indices)
        if average:
            values = tf.math.divide(values, float(size()))
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    t, ctx = compression.compress(tensor)
    summed = _allreduce(t, average=False)
    out = compression.decompress(summed, ctx)
    if average:
        out = tf.math.divide(out, float(size()))
    return out


def broadcast_variables(variables, root_rank: int = 0):
    """Assign every variable its root-rank value (reference:
    broadcast_global_variables, horovod/tensorflow/__init__.py:96-115)."""
    for var in variables:
        var.assign(broadcast(tf.convert_to_tensor(var), root_rank))


def broadcast_global_variables(root_rank: int = 0):
    """TF1-style parity name; in TF2 pass explicit variables to
    :func:`broadcast_variables`."""
    raise NotImplementedError(
        "TF2 has no global variable collection; call "
        "broadcast_variables(model.variables, root_rank) instead "
        "(reference API: horovod/tensorflow/__init__.py:96-115)")


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Keras callback broadcasting initial model+optimizer state from root
    (the TF2 form of BroadcastGlobalVariablesHook, reference:
    horovod/tensorflow/__init__.py:118-149)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_train_batch_begin(self, batch, logs=None):
        if self._done:
            return
        broadcast_variables(self.model.variables, self.root_rank)
        if getattr(self.model, "optimizer", None) is not None:
            broadcast_variables(self.model.optimizer.variables,
                                self.root_rank)
        self._done = True


class DistributedGradientTape(tf.GradientTape):
    """GradientTape whose ``gradient()`` allreduces results (reference:
    horovod/tensorflow/__init__.py:253-328)."""

    def __init__(self, *args, average: bool = True,
                 compression=Compression.none,
                 sparse_as_dense: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self._hvd_average = average
        self._hvd_compression = compression
        self._hvd_sparse_as_dense = sparse_as_dense

    def gradient(self, target, sources, output_gradients=None, **kw):
        grads = super().gradient(target, sources, output_gradients, **kw)
        return [self._reduce(g) for g in grads]

    def _reduce(self, g):
        if g is None:
            return None
        if isinstance(g, tf.IndexedSlices) and self._hvd_sparse_as_dense:
            g = tf.convert_to_tensor(g)
        return allreduce(g, average=self._hvd_average,
                         compression=self._hvd_compression)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         use_locking: bool = False, average: bool = True,
                         compression=Compression.none,
                         sparse_as_dense: bool = False):
    """Wrap a keras optimizer so gradients are allreduced before being
    applied (reference: horovod/tensorflow/__init__.py:152-250 — there it
    overrides compute_gradients; TF2's integration point is
    apply_gradients)."""

    class _Distributed(optimizer.__class__):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            reduced = []
            for g, v in gv:
                if g is None:
                    reduced.append((g, v))
                    continue
                if isinstance(g, tf.IndexedSlices) and sparse_as_dense:
                    g = tf.convert_to_tensor(g)
                reduced.append(
                    (allreduce(g, average=average, compression=compression),
                     v))
            return super().apply_gradients(reduced, *args, **kwargs)

    # Fresh instance of the dynamic subclass; slots build lazily on first
    # apply_gradients (keras 3 semantics). Wrap BEFORE any training, as the
    # reference requires (its optimizer is likewise wrapped pre-training).
    return _Distributed.from_config(optimizer.get_config())
