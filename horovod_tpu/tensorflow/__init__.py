"""TensorFlow frontend (reference: horovod/tensorflow/__init__.py).

TF computes on host CPU in this stack (the chips belong to JAX/XLA);
collectives stage through the mesh like the reference's CudaOnCPU path.
For TPU-resident TF-free training use :mod:`horovod_tpu.jax` — this
frontend exists so reference TF scripts port mechanically.
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from horovod_tpu.common.topology import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    cross_size,
    cross_rank,
    mpi_threads_supported,
)
from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401
    _allreduce,
    allgather,
    broadcast,
)


def allreduce(tensor, average: bool = True, device_dense: str = "",
              device_sparse: str = "", compression=Compression.none,
              name: Optional[str] = None):
    """Allreduce with the reference's sparse path: IndexedSlices become an
    allgather of values+indices (reference:
    horovod/tensorflow/__init__.py:48-94). A user-supplied ``name`` is
    the engine matching key — fully stable across re-traces."""
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values,
                           name=f"{name}.values" if name else None)
        indices = allgather(tensor.indices,
                            name=f"{name}.indices" if name else None)
        if average:
            values = tf.math.divide(values, float(size()))
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    from horovod_tpu.jax.compression import for_tensor as _for_tensor

    compression = _for_tensor(Compression.resolve(compression), name)
    t, ctx = compression.compress(tensor)
    summed = _allreduce(t, average=False, name=name,
                        wire=getattr(compression, "engine_wire", None))
    out = compression.decompress(summed, ctx)
    if average:
        out = tf.math.divide(out, float(size()))
    return out


def broadcast_variables(variables, root_rank: int = 0):
    """Assign every variable its root-rank value (reference:
    broadcast_global_variables, horovod/tensorflow/__init__.py:96-115)."""
    for var in variables:
        var.assign(broadcast(tf.convert_to_tensor(var), root_rank))


def bcast(root_rank: int, variables):
    """Graph-mode broadcast op over explicit variables (reference:
    horovod/tensorflow/__init__.py:106-115). Returns a grouped assign op
    to ``session.run``; under eager execution the assigns run immediately
    and the group is a no-op tensor.

    All variables ride ONE py_function (`mpi_ops._bridge_group`): the
    graph executor runs py_functions sequentially in a per-process
    order, so per-variable broadcast nodes could block cross-rank in
    different members and deadlock (r4, found by the estimator
    example)."""
    v1 = tf.compat.v1
    variables = list(variables)
    if not variables:
        return tf.group()
    from horovod_tpu.tensorflow import mpi_ops as _ops

    names = _ops._group_names(
        "broadcast", [f"{i}.{v.name}" for i, v in enumerate(variables)])
    vals = _ops._bridge_group(
        "broadcast", [tf.convert_to_tensor(v) for v in variables], names,
        root=root_rank)
    return tf.group(*[v1.assign(var, val)
                      for var, val in zip(variables, vals)])


def broadcast_global_variables(root_rank: int = 0):
    """Broadcast all global variables from ``root_rank`` (reference:
    horovod/tensorflow/__init__.py:96-104).

    Works whenever a ``tf.compat.v1`` graph/collection holds the
    variables — i.e. the reference's session-era scripts run unmodified.
    Pure-eager TF2 code has no global collection; pass explicit variables
    to :func:`broadcast_variables` instead."""
    gvars = tf.compat.v1.global_variables()
    if not gvars:
        raise NotImplementedError(
            "no tf.compat.v1 global-variable collection exists (pure-eager "
            "TF2); call broadcast_variables(model.variables, root_rank) "
            "instead (reference API: horovod/tensorflow/__init__.py:96-115)")
    return bcast(root_rank, gvars)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """SessionRunHook broadcasting all global variables from root once the
    session is created — the reference's startup-consistency hook for
    MonitoredTrainingSession scripts (reference:
    horovod/tensorflow/__init__.py:118-149). ``device`` is accepted for
    signature parity; collectives always ride the XLA mesh here."""

    def __init__(self, root_rank: int, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device

    def begin(self):
        if (self.bcast_op is None
                or self.bcast_op.graph is not tf.compat.v1.get_default_graph()):
            with tf.device(self.device or "/cpu:0"):
                self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Keras callback broadcasting initial model+optimizer state from root
    (the TF2 form of BroadcastGlobalVariablesHook, reference:
    horovod/tensorflow/__init__.py:118-149)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_train_batch_begin(self, batch, logs=None):
        if self._done:
            return
        broadcast_variables(self.model.variables, self.root_rank)
        if getattr(self.model, "optimizer", None) is not None:
            broadcast_variables(self.model.optimizer.variables,
                                self.root_rank)
        self._done = True


class DistributedGradientTape(tf.GradientTape):
    """GradientTape whose ``gradient()`` allreduces results (reference:
    horovod/tensorflow/__init__.py:253-328)."""

    def __init__(self, *args, average: bool = True,
                 compression=Compression.none,
                 sparse_as_dense: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self._hvd_average = average
        self._hvd_compression = Compression.resolve(compression)
        self._hvd_sparse_as_dense = sparse_as_dense

    def gradient(self, target, sources, output_gradients=None, **kw):
        grads = super().gradient(target, sources, output_gradients, **kw)
        # One py_function for the whole gradient list (the same
        # sequential-executor deadlock guard as the optimizers) —
        # sources stand in as the variables for naming.
        reduced = _group_reduce_grads(
            list(zip(grads, sources)), self._hvd_average,
            self._hvd_compression, self._hvd_sparse_as_dense)
        return [g for g, _ in reduced]


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         use_locking: bool = False, average: bool = True,
                         compression=Compression.none,
                         sparse_as_dense: bool = False):
    """Wrap a keras optimizer so gradients are allreduced before being
    applied (reference: horovod/tensorflow/__init__.py:152-250 — there it
    overrides compute_gradients; TF2's integration point is
    apply_gradients). Session-era ``tf.compat.v1.train`` optimizers are
    wrapped at compute_gradients exactly like the reference, so v1 graph
    scripts (e.g. the reference's tensorflow_mnist.py) run unmodified.

    ``compression`` accepts a registry name (``'int8'``/``'fp8'`` engine
    wire formats, ``'fp16'`` cast) or a compressor; unknown spellings
    fail fast HERE, naming the rank (a bad object used to surface as an
    attribute error mid-step)."""
    compression = Compression.resolve(compression)
    if isinstance(optimizer, tf.compat.v1.train.Optimizer):
        return _distributed_v1_optimizer(optimizer, average, compression,
                                         sparse_as_dense)

    # Fresh instance of the dynamic subclass; slots build lazily on first
    # apply_gradients (keras 3 semantics). Wrap BEFORE any training, as the
    # reference requires (its optimizer is likewise wrapped pre-training).
    cls = _distributed_cls(optimizer.__class__, average, compression,
                           sparse_as_dense)
    return cls.from_config(optimizer.get_config())


def _distributed_cls(base_cls, average, compression, sparse_as_dense):
    """Dynamic optimizer subclass whose apply_gradients allreduces first.

    The class keeps the BASE class's name (the reference does the same,
    horovod/_keras/__init__.py:93-109): keras serialization records the
    class name, so a model compiled with the wrapped optimizer saves as
    its underlying optimizer and :func:`load_model` can restore + re-wrap
    it — symmetric save/load."""

    class _Distributed(base_cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            reduced = _group_reduce_grads(gv, average, compression,
                                          sparse_as_dense)
            return super().apply_gradients(reduced, *args, **kwargs)

    _Distributed.__name__ = base_cls.__name__
    _Distributed.__qualname__ = base_cls.__qualname__
    # Keep the base's module too: keras 3 records (module, class_name) and
    # only imports keras-family modules on load, so without this a PLAIN
    # tf.keras.models.load_model of a wrapped save would raise instead of
    # restoring the (unwrapped) base optimizer.
    _Distributed.__module__ = base_cls.__module__
    return _Distributed


def _standard_keras_optimizers() -> list:
    """Every optimizer class reachable from tf.keras.optimizers (the
    deserialization candidates the reference enumerates as Optimizer
    subclasses, horovod/keras/__init__.py:118-148)."""
    base = tf.keras.optimizers.Optimizer
    out = []
    for attr in dir(tf.keras.optimizers):
        cls = getattr(tf.keras.optimizers, attr, None)
        if (isinstance(cls, type) and issubclass(cls, base)
                and cls is not base):
            out.append(cls)
    return out


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none, average: bool = True,
               sparse_as_dense: bool = False):
    """Load a tf.keras model saved with ``Model.save`` and re-wrap its
    optimizer in :func:`DistributedOptimizer` (reference:
    horovod/keras/__init__.py:118-148 + _keras/__init__.py:93-109 — a
    plain ``keras.models.load_model`` silently restores an UNWRAPPED
    optimizer and every process trains on its own gradients).

    Works for models saved with either a wrapped or a plain optimizer:
    the file deserializes under a scope that resolves the recorded class
    name (wrapped saves record the base optimizer's name — see
    `_distributed_cls`), then the restored instance is re-classed onto
    the distributed subclass, preserving all restored slot state
    (momentum/moments), unlike a from_config reconstruction.

    ``custom_optimizers``: extra optimizer classes needed to deserialize
    (user-defined subclasses); ``custom_objects``: forwarded to keras
    (layers, losses, ...)."""
    objs = {c.__name__: c for c in _standard_keras_optimizers()}
    for c in (custom_optimizers or []):
        objs[c.__name__] = c
    objs.update(custom_objects or {})
    with tf.keras.utils.custom_object_scope(objs):
        model = tf.keras.models.load_model(filepath)
    opt = getattr(model, "optimizer", None)
    if opt is not None and not getattr(type(opt), "_hvd_wrapped", False) \
            and not isinstance(opt, tf.compat.v1.train.Optimizer):
        opt.__class__ = _distributed_cls(type(opt), average, compression,
                                         sparse_as_dense)
    return model


def _distributed_v1_optimizer(optimizer, average, compression,
                              sparse_as_dense):
    """Dynamic subclass of a v1 optimizer overriding compute_gradients —
    the reference's integration point (horovod/tensorflow/__init__.py:
    152-250): minimize() calls compute_gradients, each gradient gets an
    allreduce node, apply_gradients consumes the reduced values."""

    class _DistributedV1(optimizer.__class__):
        _hvd_wrapped = True

        def __init__(self):
            # State was fully built by the user's constructor; reuse it.
            self.__dict__.update(optimizer.__dict__)

        def compute_gradients(self, *args, **kwargs):
            gradients = super().compute_gradients(*args, **kwargs)
            return _group_reduce_grads(gradients, average, compression,
                                       sparse_as_dense)

    return _DistributedV1()


def _group_reduce_grads(grads_and_vars, average, compression,
                        sparse_as_dense):
    """Reduce every gradient of a step through ONE py_function
    (`mpi_ops._bridge_group` — see its docstring for why per-gradient
    nodes can deadlock a v1 graph's sequential py_function executor).
    Dense gradients are allreduced; sparse IndexedSlices ride the
    reference's allgather-of-values+indices path (reference:
    horovod/tensorflow/__init__.py:48-94) INSIDE the same group — a
    separate sparse py_function would re-create the cross-rank wedge
    the grouping exists to prevent."""
    from horovod_tpu.jax.compression import for_tensor as _for_tensor
    from horovod_tpu.tensorflow import mpi_ops as _ops

    compression = Compression.resolve(compression)
    gv = [(tf.convert_to_tensor(g), v)
          if isinstance(g, tf.IndexedSlices) and sparse_as_dense else (g, v)
          for g, v in grads_and_vars]
    kinds, tensors, labels, roles, wires = [], [], [], [], []
    for i, (g, v) in enumerate(gv):
        # Position index keeps labels unique (keras-3 variable names are
        # bare "kernel"/"bias"); positions are rank-consistent because
        # every controller builds the same gradient list.
        vname = getattr(v, "name", "t")
        if g is None:
            continue
        if isinstance(g, tf.IndexedSlices):
            kinds += ["allgather", "allgather"]
            tensors += [g.values, g.indices]
            labels += [f"DistributedOptimizer.{i}.{vname}.values",
                       f"DistributedOptimizer.{i}.{vname}.indices"]
            roles += [("sparse_values", i), ("sparse_indices", i)]
            wires += [None, None]
        else:
            # Per-tensor policy resolution by variable name (the
            # Compression.select overrides); the engine wire format
            # rides the request, cast compressors wrap it here.
            comp = _for_tensor(compression, vname)
            t, ctx = comp.compress(g)
            kinds.append("allreduce")
            tensors.append(t)
            labels.append(f"DistributedOptimizer.{i}.{vname}")
            roles.append(("dense", i, ctx, comp))
            wires.append(getattr(comp, "engine_wire", None))
    out = [(g, v) for g, v in gv]
    if not tensors:
        return out
    names = _ops._group_names("allreduce", labels)
    results = _ops._bridge_group(kinds, tensors, names, average=False,
                                 wires=wires)
    sparse_parts = {}
    for role, res in zip(roles, results):
        if role[0] == "dense":
            _, i, ctx, comp = role
            g = comp.decompress(res, ctx)
            if average:
                g = tf.math.divide(g, float(size()))
            out[i] = (g, gv[i][1])
        else:
            sparse_parts.setdefault(role[1], {})[role[0]] = res
    for i, parts in sparse_parts.items():
        values = parts["sparse_values"]
        if average:
            values = tf.math.divide(values, float(size()))
        out[i] = (tf.IndexedSlices(values, parts["sparse_indices"],
                                   dense_shape=gv[i][0].dense_shape),
                  gv[i][1])
    return out
