"""Gradient compression for the TF frontend (reference:
horovod/tensorflow/compression.py)."""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    """Interface (reference: tensorflow/compression.py:23-34)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast-down/cast-up (reference: tensorflow/compression.py:46-64).
    On TPU the 16-bit wire dtype is bfloat16 — same exponent range as f32,
    so gradient casts cannot overflow the way fp16 can."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if tensor.dtype.is_floating:
            tensor = tf.cast(tensor, tf.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and ctx.is_floating:
            tensor = tf.cast(tensor, ctx)
        return tensor


class Compression:
    """Reference: tensorflow/compression.py:67-74."""

    none = NoneCompressor
    fp16 = FP16Compressor
