"""Gradient compression for the TF frontend (reference:
horovod/tensorflow/compression.py).

Cast policies (``fp16`` — bf16 on the wire, TPU-native) wrap the
collective with compress/decompress as in the reference. The quantized
block-scaled policies (``int8``/``fp8`` — jax/quantize.py) are applied
inside the ENGINE's execution chunks instead: their TF compressors are
identity pass-throughs that tag the request with ``engine_wire`` so the
shared data plane quantizes per chunk (summing int8 payloads through a
plain allreduce would saturate). ``Compression.resolve`` fails fast with
rank attribution on unknown spellings — a bad compressor used to
surface as an attribute error mid-step."""

from __future__ import annotations

import tensorflow as tf

from horovod_tpu.jax.compression import resolve_in, select_in


class Compressor:
    """Interface (reference: tensorflow/compression.py:23-34)."""

    engine_wire = None

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast-down/cast-up (reference: tensorflow/compression.py:46-64).
    On TPU the 16-bit wire dtype is bfloat16 — same exponent range as f32,
    so gradient casts cannot overflow the way fp16 can."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if tensor.dtype.is_floating:
            tensor = tf.cast(tensor, tf.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and ctx.is_floating:
            tensor = tf.cast(tensor, ctx)
        return tensor


class Int8Compressor(NoneCompressor):
    """Block-scaled int8 on the engine wire (jax/quantize.py): identity
    at the TF layer, quantized per execution chunk in the data plane."""

    engine_wire = "int8"


class FP8Compressor(NoneCompressor):
    """Block-scaled fp8 (e4m3) on the engine wire."""

    engine_wire = "fp8"


class Compression:
    """Reference: tensorflow/compression.py:67-74."""

    none = NoneCompressor
    fp16 = FP16Compressor
    int8 = Int8Compressor
    fp8 = FP8Compressor

    _registry = {"none": NoneCompressor, "fp16": FP16Compressor,
                 "int8": Int8Compressor, "fp8": FP8Compressor}

    @classmethod
    def resolve(cls, spec, where: str = "compression"):
        return resolve_in(cls._registry, spec, where)

    @classmethod
    def select(cls, default="none", **overrides):
        """Name-based per-tensor policy (fnmatch on the variable name;
        first keyword match wins). Members are explicit: a ``'none'``
        entry pins full width even under an HVD_COMPRESSION default."""
        return select_in(cls.resolve, default, overrides)
