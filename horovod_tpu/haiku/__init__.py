"""dm-haiku frontend.

The reference ships four frontends (TF, torch, standalone Keras, tf.keras
— SURVEY.md §1 L4); haiku fills the "second JAX-native frontend" seat
here. Haiku is functional like flax, so the integration surface is thin:
the same optax ``DistributedOptimizer`` wrapper, parameter/state broadcast
for ``hk.transform`` param trees, and distributed grad helpers.
"""

from __future__ import annotations

from horovod_tpu.common.topology import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    cross_size,
    cross_rank,
    mesh,
)
from horovod_tpu.jax import (  # noqa: F401
    Compression,
    DistributedOptimizer,
    allreduce,
    allreduce_pytree,
    broadcast_object,
    broadcast_pytree,
    grad,
    jit,
    value_and_grad,
)
from horovod_tpu.ops.collectives import (  # noqa: F401
    HVD_AXIS,
    allgather,
    axis_rank,
    broadcast,
)


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast an ``hk.Params`` tree from root (haiku params are plain
    nested dicts of arrays — one fused broadcast per dtype)."""
    return broadcast_pytree(params, root_rank=root_rank)


def broadcast_state(state, root_rank: int = 0):
    """Broadcast ``hk.State`` (batch norm statistics etc.)."""
    return broadcast_pytree(state, root_rank=root_rank)


def average_state(state):
    """Average ``hk.State`` across ranks — batch-norm statistics are
    per-replica during training (never allreduced, matching the
    reference's BN semantics); average them once before evaluation or
    checkpointing so every rank scores the same model.

    The mean is computed INSIDE the mesh (psum over the hvd axis):
    per-chip statistics live in arrays whose sharding claims
    replication while chips disagree, so any host-side fetch would read
    ONE chip's values and silently discard the rest. Counters and other
    integer state are averaged in float and cast back.

    The compiled averager is cached per world mesh (hvd.jit binds the
    mesh at decoration time), so a per-epoch eval pays one trace/compile
    per world, not per call."""
    m = mesh()
    avg = _AVG_CACHE.get(id(m))
    if avg is None:
        import jax.numpy as jnp
        import jax.tree_util as jtu
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import jax as hvd_jax

        @hvd_jax.jit(in_specs=(P(),), out_specs=P())
        def avg(tree):
            return jtu.tree_map(
                lambda l: allreduce(jnp.asarray(l, jnp.float32),
                                    average=True).astype(
                                        jnp.asarray(l).dtype),
                tree)

        _AVG_CACHE.clear()  # old worlds' programs are unusable anyway
        _AVG_CACHE[id(m)] = avg
    return avg(state)


_AVG_CACHE: dict = {}
