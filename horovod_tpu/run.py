"""Local multi-process launcher — the role ``mpirun`` plays for the
reference (reference: docs/running.md tells users to invoke
``mpirun -np N python train.py``; there is no launcher in-tree at v0.15.2).

    python -m horovod_tpu.run -np 2 python train.py --epochs 1

Spawns N controller processes wired together through ``jax.distributed``
(coordinator on a free localhost port). On a CPU host each process gets
``--ncpus-per-proc`` virtual chips so an N-process × M-chip world can be
simulated exactly like the reference's single-host ``mpirun -np N`` test
tier (SURVEY.md §4). On real multi-host TPU pods, prefer one process per
host started by your scheduler; this launcher is for local runs and tests.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, ""):
        out.write(f"{prefix}{line}")
        out.flush()
    pipe.close()


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.run",
        description="Launch N local horovod_tpu controller processes.")
    ap.add_argument("-np", "--num-proc", type=int, required=True)
    ap.add_argument("--ncpus-per-proc", type=int, default=4,
                    help="virtual CPU chips per process (CPU simulation)")
    ap.add_argument("--cpu", action="store_true", default=False,
                    help="force the CPU platform (default: inherit)")
    ap.add_argument("--tag-output", action="store_true", default=True)
    ap.add_argument("--timeline", metavar="DIR", default=None,
                    help="distributed tracing: every process writes "
                         "timeline.rank{N}.json into DIR (sets "
                         "HVD_TIMELINE), and the launcher merges them "
                         "into one Perfetto trace at exit")
    ap.add_argument("--telemetry-port-base", type=int, metavar="PORT",
                    default=None,
                    help="live telemetry: process i serves /metrics and "
                         "/healthz on 127.0.0.1:PORT+i (sets "
                         "HVD_TELEMETRY_PORT; query with "
                         "python -m horovod_tpu.utils.stats "
                         "http://127.0.0.1:PORT)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run, e.g. python train.py --epochs 1")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    cmd = args.command
    if cmd[0] == "--":
        cmd = cmd[1:]

    # Distributed tracing: --timeline DIR (or an inherited HVD_TIMELINE)
    # rides into every child; children resolve their own per-rank file
    # from HVD_PROCESS_ID (core/timeline.py), the launcher auto-merges.
    timeline = args.timeline or os.environ.get("HVD_TIMELINE") \
        or os.environ.get("HOROVOD_TIMELINE")
    timeline_dir = None
    if timeline:
        from horovod_tpu.core.timeline import is_dir_mode

        if is_dir_mode(timeline):
            os.makedirs(timeline, exist_ok=True)
            timeline_dir = timeline
            # A reused dir must not leak a previous run's ranks into the
            # merge: a -np 2 rerun over an old -np 4 capture would
            # attribute waits to ranks that were never in this world.
            import glob as _glob

            for stale in _glob.glob(
                    os.path.join(timeline, "timeline.rank*.json")) + \
                    _glob.glob(os.path.join(timeline,
                                            "timeline.merged.json")):
                try:
                    os.unlink(stale)
                except OSError:
                    pass
        elif args.num_proc > 1:
            # N children opening ONE .json would clobber each other into
            # an interleaved, unloadable trace — and there would be
            # nothing to merge. Refuse loudly instead of corrupting.
            ap.error(
                f"--timeline/HVD_TIMELINE={timeline} is a single file; "
                f"{args.num_proc} processes need a directory "
                "(per-rank traces + auto-merge)")

    port = _free_port()
    procs = []
    threads = []
    for i in range(args.num_proc):
        env = dict(os.environ)
        env["HVD_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["HVD_NUM_PROCESSES"] = str(args.num_proc)
        env["HVD_PROCESS_ID"] = str(i)
        if timeline:
            env["HVD_TIMELINE"] = timeline
        if args.telemetry_port_base is not None:
            env["HVD_TELEMETRY_PORT"] = str(args.telemetry_port_base + i)
        if args.cpu:
            # HVD_PLATFORM is applied via jax.config inside hvd.init()
            # (plain JAX_PLATFORMS can be preempted by plugins).
            env["HVD_PLATFORM"] = "cpu"
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count="
                f"{args.ncpus_per_proc}").strip()
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        prefix = f"[{i}] " if args.tag_output else ""
        t = threading.Thread(target=_stream, args=(prefix, p.stdout,
                                                   sys.stdout), daemon=True)
        t.start()
        threads.append(t)

    def _kill_all(signum=None, frame=None):
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGINT, _kill_all)
    signal.signal(signal.SIGTERM, _kill_all)

    # Poll ALL children each tick (mpirun semantics: first failure tears
    # down the whole job). A sequential wait() would never observe a
    # higher-index child dying while process 0 blocks in a collective.
    rc = 0
    pending = set(range(len(procs)))
    while pending:
        exited = [i for i in pending if procs[i].poll() is not None]
        for i in exited:
            pending.discard(i)
            code = procs[i].returncode
            if code != 0 and rc == 0:
                rc = code
                sys.stderr.write(
                    f"process {i} exited with code {code}; "
                    "terminating the remaining processes\n")
                _kill_all()
        if pending and not exited:
            time.sleep(0.05)
    for t in threads:
        t.join(timeout=5)
    if timeline_dir:
        # Collect + auto-merge the per-rank traces (whatever landed on
        # disk — the truncation-tolerant reader handles ranks that died
        # mid-write). Best-effort: a merge failure must not change the
        # job's exit code.
        try:
            from horovod_tpu.utils import trace as trace_mod

            info = trace_mod.merge(timeline_dir)
            sys.stderr.write(
                f"[launcher] merged timeline: {info['files']} rank "
                f"file(s), {info['events']} events -> {info['path']}\n")
        except Exception as exc:
            sys.stderr.write(f"[launcher] timeline merge failed: {exc}\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
