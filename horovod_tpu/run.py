"""Local multi-process launcher — the role ``mpirun`` plays for the
reference (reference: docs/running.md tells users to invoke
``mpirun -np N python train.py``; there is no launcher in-tree at v0.15.2).

    python -m horovod_tpu.run -np 2 python train.py --epochs 1

Spawns N controller processes wired together through ``jax.distributed``
(coordinator on a free localhost port). On a CPU host each process gets
``--ncpus-per-proc`` virtual chips so an N-process × M-chip world can be
simulated exactly like the reference's single-host ``mpirun -np N`` test
tier (SURVEY.md §4). On real multi-host TPU pods, prefer one process per
host started by your scheduler; this launcher is for local runs and tests.

Failure semantics:

- Default (``mpirun`` parity): the first child death is REPORTED — which
  rank, which pid, which signal or exit code — before the remaining
  children are torn down, and that child's status becomes the
  launcher's own (``128+signum`` for signal deaths).
- ``--elastic`` (supervisor mode, core/elastic.py): children run with
  ``HVD_ELASTIC=1`` and are *supervised*, not collectively killed. A
  crashed/killed child gets a death note; survivors keep training on a
  shrunk world; after an ``HVD_ELASTIC_BLACKLIST_S`` backoff (doubled
  per repeat death, capped by ``--max-restarts``) the supervisor files a
  rejoin request, survivors checkpoint and exit with the restart code,
  and the whole world is relaunched at the next generation — resuming
  from the newest checkpoint with the recovered rank readmitted.
  ``--min-np`` bounds how far the world may shrink in place.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, ""):
        out.write(f"{prefix}{line}")
        out.flush()
    pipe.close()


def _describe_exit(rank: int, pid: int, code: int) -> str:
    """Human attribution of one child's exit (the satellite the old
    launcher lacked: *which* rank died, *how*, before the teardown)."""
    if code < 0:
        try:
            name = signal.Signals(-code).name
        except ValueError:
            name = f"signal {-code}"
        return f"rank {rank} (pid {pid}) was killed by {name}"
    return f"rank {rank} (pid {pid}) exited with code {code}"


def _exit_status(code: int) -> int:
    """Shell-convention launcher status for a child status: 128+signum
    for signal deaths (a raw negative returncode would be truncated to a
    meaningless byte), the child's own code otherwise."""
    return 128 - code if code < 0 else code


# Keep in sync with horovod_tpu.core.elastic.RESTART_EXIT_CODE (pinned by
# tests/test_world_elastic.py); importing the module here would drag jax
# into the launcher process.
RESTART_EXIT_CODE = 77


def _graceful_stop(procs, grace_s: float, signum: int) -> int:
    """Graceful preemption drain (the launcher half of the ladder in
    core/preempt.py): forward SIGTERM to every live child, wait up to
    ``grace_s`` for them to drain/checkpoint/exit on their own, and
    escalate to SIGKILL only for the stragglers — reporting which
    children exited clean vs were escalated. Returns the launcher
    status: 0 when every child exited 0 (a fully clean eviction),
    128+signum otherwise."""
    alive = [i for i, p in enumerate(procs) if p.poll() is None]
    sys.stderr.write(
        f"[launcher] {signal.Signals(signum).name} received: forwarding "
        f"to {len(alive)} child(ren) and draining up to "
        f"{grace_s:.0f}s before escalating\n")
    for i in alive:
        try:
            procs[i].terminate()  # SIGTERM: the child's graceful ladder
        except OSError:
            pass
    deadline = time.monotonic() + max(0.0, grace_s)
    reported: set = set()
    while time.monotonic() < deadline:
        for i, p in enumerate(procs):
            if i in reported or p.poll() is None:
                continue
            reported.add(i)
            if p.returncode == 0:
                sys.stderr.write(f"[launcher] rank {i} (pid {p.pid}) "
                                 "exited clean during the drain\n")
            else:
                sys.stderr.write(
                    "[launcher] "
                    + _describe_exit(i, p.pid, p.returncode)
                    + " during the drain\n")
        if all(p.poll() is not None for p in procs):
            break
        time.sleep(0.05)
    escalated = [i for i, p in enumerate(procs) if p.poll() is None]
    for i in escalated:
        sys.stderr.write(
            f"[launcher] rank {i} (pid {procs[i].pid}) did not exit "
            f"within --grace-s={grace_s:.0f}; escalating to SIGKILL\n")
        try:
            procs[i].kill()
        except OSError:
            pass
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:
            pass
    clean = all(p.returncode == 0 for p in procs)
    sys.stderr.write(
        f"[launcher] drain complete: "
        f"{sum(1 for p in procs if p.returncode == 0)} clean, "
        f"{len(escalated)} escalated\n")
    return 0 if clean else 128 + signum


def _run_failfast(args, spawn_world) -> int:
    """mpirun parity: first child death tears the world down — after an
    attributed report of who died and how. A sequential wait() would
    never observe a higher-index child dying while process 0 blocks in a
    collective, hence the poll loop. SIGTERM (the platform's eviction
    signal) is NOT a teardown: it is forwarded and the children get
    ``--grace-s`` to drain before the SIGKILL escalation."""
    procs, threads = spawn_world({})

    def _kill_all(signum=None, frame=None):
        # Casualty/interactive teardown: SIGTERM first, but children now
        # TRAP it for the graceful-preemption ladder — a survivor blocked
        # inside a cross-rank collective never reaches the batch-boundary
        # poll, so escalate to SIGKILL after a SHORT window. This is a
        # crash teardown, not an eviction: nobody gets --grace-s here
        # (mpirun parity — quick, bounded, never wedged).
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs):
                return
            time.sleep(0.05)
        for p in procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass

    sigterm = []

    def _on_term(signum, frame):
        sigterm.append(signum)  # handled by the poll loop, not inline

    signal.signal(signal.SIGINT, _kill_all)
    signal.signal(signal.SIGTERM, _on_term)

    rc = 0
    pending = set(range(len(procs)))
    while pending:
        if sigterm:
            rc = _graceful_stop(procs, args.grace_s, sigterm[0])
            pending.clear()
            break
        exited = [i for i in pending if procs[i].poll() is not None]
        for i in exited:
            pending.discard(i)
            code = procs[i].returncode
            if code != 0 and rc == 0:
                # The FIRST failure is the cause; children _kill_all
                # subsequently terminates (SIGTERM, code -15) are
                # casualties, not causes — the cause's status is the
                # launcher's status (128+signum for a signal death; the
                # old launcher returned the raw negative, which the
                # shell mangled into its own meaningless byte).
                rc = _exit_status(code)
                sys.stderr.write(
                    "[launcher] " + _describe_exit(i, procs[i].pid, code)
                    + "; terminating the remaining processes\n")
                _kill_all()
        if pending and not exited:
            time.sleep(0.05)
    for t in threads:
        t.join(timeout=5)
    return rc


def _parse_faults(entries) -> dict:
    """``--faults RANK:SPEC`` (repeatable) -> {rank: spec}. Several
    entries for one rank join with commas (the HVD_FAULTS grammar).
    Specs are validated HERE, before any child spawns: a typo'd site or
    mode must fail the launch, not crash-loop every relaunched
    generation through an import-time FaultSpecError in the child.
    (core.faultline is stdlib-only — importing it does not drag jax
    into the launcher process.)"""
    from horovod_tpu.core import faultline as _faultline

    out: dict = {}
    for entry in entries or ():
        rank_s, sep, spec = entry.partition(":")
        try:
            rank = int(rank_s)
        except ValueError:
            rank = -1
        if not sep or rank < 0 or not spec:
            raise SystemExit(
                f"--faults {entry!r}: want RANK:SPEC (e.g. "
                "1:hb.beat:skip:*)")
        try:
            _faultline._parse(spec)
        except _faultline.FaultSpecError as exc:
            raise SystemExit(f"--faults {entry!r}: {exc}") from None
        out[rank] = (out[rank] + "," + spec) if rank in out else spec
    return out


def _prune_elastic_dir(edir: str, generation: int):
    """Supervisor hygiene: consumed control files from generation N-2
    and older are dropped at relaunch — death notes, rejoin requests,
    restart votes and the fallback-KV namespace otherwise accumulate
    forever across a long-lived elastic job. Checkpoints and the epoch
    journal are never touched (they ARE the resume state)."""
    floor = generation - 1  # keep the previous generation for forensics

    def gen_of(path):
        try:
            with open(path) as fh:
                return int(json.load(fh).get("generation", -1))
        except (OSError, ValueError, TypeError):
            return None

    # (rejoin requests need no generation filter here: the supervisor
    # loop already consumes the WHOLE rejoin dir right after this prune,
    # every relaunch.)
    d = os.path.join(edir, "death")
    if os.path.isdir(d):
        for name in os.listdir(d):
            path = os.path.join(d, name)
            g = gen_of(path)
            if g is not None and g < floor:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    kv = os.path.join(edir, "kv")
    if os.path.isdir(kv):
        # Fallback-plane keys are namespaced hvd~elastic~g<gen>[~...]
        # (core/elastic.py FileKV): prune whole dead generations.
        for name in os.listdir(kv):
            if not name.startswith("hvd~elastic~g"):
                continue
            head = name[len("hvd~elastic~g"):].split("~", 1)[0]
            head = head.split(".", 1)[0]  # tmp suffixes
            try:
                g = int(head)
            except ValueError:
                continue
            if g < floor:
                try:
                    os.unlink(os.path.join(kv, name))
                except OSError:
                    pass


def _supervise_elastic(args, spawn_world) -> int:
    """Elastic supervisor (core/elastic.py): children survive peer
    death; this loop supplies the process-management half — death notes,
    blacklist-then-readmit rejoin requests, and capped full-world
    relaunches when the members vote for a coordinated restart."""
    import tempfile

    edir = args.elastic_dir or os.environ.get("HVD_ELASTIC_DIR") \
        or tempfile.mkdtemp(prefix="hvd_elastic_")
    os.makedirs(edir, exist_ok=True)
    sys.stderr.write(f"[launcher] elastic supervisor: dir {edir}, "
                     f"min-np {args.min_np}, "
                     f"max-restarts {args.max_restarts}\n")
    restarts = {i: 0 for i in range(args.num_proc)}
    faults_by_rank = getattr(args, "_faults_by_rank", {}) or {}
    world_relaunches = 0
    generation = 0
    interrupted = []

    def _on_signal(signum, frame):
        interrupted.append(signum)

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)

    # Read the knob from env directly — importing core.elastic here
    # would drag jax (and the TPU plugin) into the supervisor process,
    # the same reason RESTART_EXIT_CODE is duplicated above. Keep the
    # default in sync with core/elastic.py blacklist_s().
    try:
        blacklist = float(os.environ.get("HVD_ELASTIC_BLACKLIST_S", "5"))
    except ValueError:
        blacklist = 5.0

    while True:
        # Hygiene: control files (death notes, rejoin requests, restart
        # votes, fallback-KV keys) from generation N-2 and older are
        # consumed — prune them so HVD_ELASTIC_DIR stays bounded across
        # a long-lived job's relaunches.
        _prune_elastic_dir(edir, generation)
        # Consume control files from the previous generation: a stale
        # rejoin request would bounce the fresh world straight back into
        # a restart loop.
        for name in ("restart.json",):
            try:
                os.unlink(os.path.join(edir, name))
            except OSError:
                pass
        rejoin_dir = os.path.join(edir, "rejoin")
        if os.path.isdir(rejoin_dir):
            for f in os.listdir(rejoin_dir):
                try:
                    os.unlink(os.path.join(rejoin_dir, f))
                except OSError:
                    pass

        procs, threads = spawn_world({
            "HVD_ELASTIC": "1",
            "HVD_ELASTIC_DIR": edir,
            "HVD_ELASTIC_GENERATION": str(generation),
            "HVD_ELASTIC_MIN_NP": str(args.min_np),
        })
        statuses: dict = {}
        rejoin_due: dict = {}
        while len(statuses) < len(procs) and not interrupted:
            for i, p in enumerate(procs):
                if i in statuses or p.poll() is None:
                    continue
                code = p.returncode
                statuses[i] = code
                desc = _describe_exit(i, p.pid, code)
                if code == RESTART_EXIT_CODE:
                    sys.stderr.write(f"[launcher] {desc} "
                                     "(coordinated-restart vote)\n")
                elif code == 0:
                    sys.stderr.write(f"[launcher] rank {i} (pid {p.pid}) "
                                     "completed\n")
                else:
                    # Injections are armed in generation 0 only: a
                    # gen>0 crash is organic and must never be reported
                    # as injected (the misattribution this PR exists to
                    # prevent).
                    injected = (faults_by_rank.get(i)
                                if generation == 0 else None)
                    if injected:
                        # The death report must say the child ran with
                        # ARMED injections: a chaos casualty must never
                        # read as an organic incident in a post-mortem.
                        desc += (f" (this rank had active fault "
                                 f"injections: {injected})")
                    sys.stderr.write(
                        f"[launcher] {desc}; elastic world continues "
                        "degraded\n")
                    try:
                        os.makedirs(os.path.join(edir, "death"),
                                    exist_ok=True)
                        note = {"process": i, "pid": p.pid,
                                "status": code,
                                "generation": generation,
                                "wall": round(time.time(), 3)}
                        if injected:
                            note["faults"] = injected
                        with open(os.path.join(
                                edir, "death",
                                f"p{i}.supervisor.json"), "w") as fh:
                            json.dump(note, fh)
                    except OSError:
                        pass
                    if restarts[i] < args.max_restarts:
                        backoff = blacklist * (2 ** restarts[i])
                        restarts[i] += 1
                        rejoin_due[i] = time.monotonic() + backoff
                        sys.stderr.write(
                            f"[launcher] rank {i} blacklisted for "
                            f"{backoff:.1f}s before readmission "
                            f"(restart {restarts[i]}/"
                            f"{args.max_restarts})\n")
                    else:
                        sys.stderr.write(
                            f"[launcher] rank {i} exceeded "
                            f"--max-restarts={args.max_restarts}; "
                            "not readmitting\n")
            # A rank can be lease-verdicted by its peers while its
            # process is WEDGED rather than dead (blocked inside the
            # runtime): the survivors' death notes name it — reap it,
            # or the wait loop above blocks on it forever.
            death_dir = os.path.join(edir, "death")
            if os.path.isdir(death_dir):
                for i, p in enumerate(procs):
                    if i in statuses or p.poll() is not None:
                        continue
                    note = os.path.join(death_dir, f"p{i}.json")
                    try:
                        with open(note) as fh:
                            rec = json.load(fh)
                    except (OSError, ValueError):
                        continue
                    if rec.get("generation") == generation:
                        injected = (faults_by_rank.get(i)
                                    if generation == 0 else None)
                        extra = (f" (this rank had active fault "
                                 f"injections: {injected})"
                                 if injected else "")
                        sys.stderr.write(
                            f"[launcher] rank {i} (pid {p.pid}) was "
                            "declared dead by its peers but is still "
                            f"running (wedged); killing it{extra}\n")
                        p.kill()
            now = time.monotonic()
            for i in [i for i, due in rejoin_due.items() if now >= due]:
                del rejoin_due[i]
                try:
                    os.makedirs(rejoin_dir, exist_ok=True)
                    with open(os.path.join(rejoin_dir, f"p{i}.json"),
                              "w") as fh:
                        json.dump({"process": i, "generation": generation,
                                   "wall": round(time.time(), 3)}, fh)
                    sys.stderr.write(
                        f"[launcher] rank {i} blacklist expired; rejoin "
                        "request filed (survivors restart at their next "
                        "epoch boundary)\n")
                except OSError as exc:
                    sys.stderr.write(
                        f"[launcher] cannot file rejoin request: {exc}\n")
            time.sleep(0.05)
        if interrupted:
            if signal.SIGTERM in interrupted:
                # Platform eviction: forward, grace-drain, escalate —
                # same ladder as the non-elastic launcher.
                return _graceful_stop(procs, args.grace_s,
                                      signal.SIGTERM)
            # SIGINT (interactive): quick teardown — children trap
            # SIGTERM (preempt intake), so a short SIGKILL escalation
            # keeps "quick" true instead of leaving drain-laddering
            # orphans behind the returned prompt.
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            deadline = time.monotonic() + 5.0
            while (time.monotonic() < deadline
                   and any(p.poll() is None for p in procs)):
                time.sleep(0.05)
            for p in procs:
                if p.poll() is None:
                    try:
                        p.kill()
                    except OSError:
                        pass
            return 130
        for t in threads:
            t.join(timeout=5)

        votes = sorted(i for i, c in statuses.items()
                       if c == RESTART_EXIT_CODE)
        completed = sorted(i for i, c in statuses.items() if c == 0)
        crashed = sorted(i for i, c in statuses.items()
                         if c not in (0, RESTART_EXIT_CODE))
        if completed and not votes:
            # The job finished (possibly degraded — a crashed rank that
            # was never readmitted is reported above, not fatal).
            return 0
        if (votes or crashed) and world_relaunches < args.max_restarts:
            world_relaunches += 1
            generation += 1
            sys.stderr.write(
                f"[launcher] relaunching the world: generation "
                f"{generation} (votes {votes}, crashed {crashed}, "
                f"relaunch {world_relaunches}/{args.max_restarts})\n")
            continue
        if crashed:
            code = statuses[crashed[0]]
            sys.stderr.write(
                "[launcher] giving up: relaunch budget exhausted\n")
            return _exit_status(code)
        if votes:
            # Members exited mid-training expecting a relaunch the
            # budget no longer allows — that is an incomplete job, not
            # a success.
            sys.stderr.write(
                "[launcher] giving up: relaunch budget exhausted with "
                f"pending restart votes from ranks {votes}\n")
            return 1
        return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.run",
        description="Launch N local horovod_tpu controller processes.")
    ap.add_argument("-np", "--num-proc", type=int, required=True)
    ap.add_argument("--ncpus-per-proc", type=int, default=4,
                    help="virtual CPU chips per process (CPU simulation)")
    ap.add_argument("--cpu", action="store_true", default=False,
                    help="force the CPU platform (default: inherit)")
    ap.add_argument("--tag-output", action="store_true", default=True)
    ap.add_argument("--timeline", metavar="DIR", default=None,
                    help="distributed tracing: every process writes "
                         "timeline.rank{N}.json into DIR (sets "
                         "HVD_TIMELINE), and the launcher merges them "
                         "into one Perfetto trace at exit")
    ap.add_argument("--telemetry-port-base", type=int, metavar="PORT",
                    default=None,
                    help="live telemetry: process i serves /metrics and "
                         "/healthz on 127.0.0.1:PORT+i (sets "
                         "HVD_TELEMETRY_PORT; query with "
                         "python -m horovod_tpu.utils.stats "
                         "http://127.0.0.1:PORT)")
    ap.add_argument("--elastic", action="store_true", default=False,
                    help="supervisor mode: children run with "
                         "HVD_ELASTIC=1, a dead rank does not kill the "
                         "world, and recovered ranks rejoin at an epoch "
                         "boundary through a full-world relaunch "
                         "(docs/running.md 'Elastic worlds')")
    ap.add_argument("--min-np", type=int, default=1, metavar="K",
                    help="elastic: smallest process count the world may "
                         "shrink to in place; below it survivors wait "
                         "for a relaunch (default 1)")
    ap.add_argument("--max-restarts", type=int, default=3, metavar="N",
                    help="elastic: per-rank readmissions and full-world "
                         "relaunches allowed before giving up "
                         "(default 3)")
    ap.add_argument("--grace-s", type=float, default=30.0, metavar="S",
                    help="graceful preemption: on SIGTERM, forward the "
                         "signal to every child and wait S seconds for "
                         "them to drain/checkpoint/exit 0 before "
                         "escalating to SIGKILL (default 30; both "
                         "elastic and plain modes)")
    ap.add_argument("--faults", action="append", metavar="RANK:SPEC",
                    default=None,
                    help="fault injection (core/faultline.py): arm "
                         "HVD_FAULTS=SPEC in rank RANK's child only "
                         "(repeatable; e.g. --faults "
                         "'1:hb.beat:skip:*' freezes rank 1's "
                         "heartbeat). Scoped to generation 0 — "
                         "relaunched generations run clean. The "
                         "supervisor's death report names a dead "
                         "child's active injections")
    ap.add_argument("--elastic-dir", default=None, metavar="DIR",
                    help="elastic: state directory shared with the "
                         "children (epoch journal, death notes, rejoin "
                         "requests, checkpoints; default "
                         "HVD_ELASTIC_DIR or a fresh temp dir)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run, e.g. python train.py --epochs 1")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    cmd = args.command
    if cmd[0] == "--":
        cmd = cmd[1:]
    args._faults_by_rank = _parse_faults(args.faults)
    for r in args._faults_by_rank:
        if r >= args.num_proc:
            ap.error(f"--faults rank {r} outside the -np "
                     f"{args.num_proc} world")

    # Distributed tracing: --timeline DIR (or an inherited HVD_TIMELINE)
    # rides into every child; children resolve their own per-rank file
    # from HVD_PROCESS_ID (core/timeline.py), the launcher auto-merges.
    timeline = args.timeline or os.environ.get("HVD_TIMELINE") \
        or os.environ.get("HOROVOD_TIMELINE")
    timeline_dir = None
    if timeline:
        from horovod_tpu.core.timeline import is_dir_mode

        if is_dir_mode(timeline):
            os.makedirs(timeline, exist_ok=True)
            timeline_dir = timeline
            # A reused dir must not leak a previous run's ranks into the
            # merge: a -np 2 rerun over an old -np 4 capture would
            # attribute waits to ranks that were never in this world.
            import glob as _glob

            for stale in _glob.glob(
                    os.path.join(timeline, "timeline.rank*.json")) + \
                    _glob.glob(os.path.join(timeline,
                                            "timeline.merged.json")):
                try:
                    os.unlink(stale)
                except OSError:
                    pass
        elif args.num_proc > 1:
            # N children opening ONE .json would clobber each other into
            # an interleaved, unloadable trace — and there would be
            # nothing to merge. Refuse loudly instead of corrupting.
            ap.error(
                f"--timeline/HVD_TIMELINE={timeline} is a single file; "
                f"{args.num_proc} processes need a directory "
                "(per-rank traces + auto-merge)")

    def _spawn_world(extra_env: dict):
        port = _free_port()
        procs, threads = [], []
        for i in range(args.num_proc):
            env = dict(os.environ)
            env["HVD_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
            env["HVD_NUM_PROCESSES"] = str(args.num_proc)
            env["HVD_PROCESS_ID"] = str(i)
            if (i in args._faults_by_rank
                    and extra_env.get("HVD_ELASTIC_GENERATION",
                                      "0") == "0"):
                # Per-rank fault scope: the spec reaches ONE child, and
                # only the FIRST world — a relaunched generation exists
                # to prove a clean resume, and re-arming the same fault
                # there would crash-loop it through the whole restart
                # budget.
                env["HVD_FAULTS"] = args._faults_by_rank[i]
            env.update(extra_env)
            if timeline:
                env["HVD_TIMELINE"] = timeline
            if args.telemetry_port_base is not None:
                env["HVD_TELEMETRY_PORT"] = str(
                    args.telemetry_port_base + i)
            if args.cpu:
                # HVD_PLATFORM is applied via jax.config inside hvd.init()
                # (plain JAX_PLATFORMS can be preempted by plugins).
                env["HVD_PLATFORM"] = "cpu"
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "") +
                    f" --xla_force_host_platform_device_count="
                    f"{args.ncpus_per_proc}").strip()
            p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
            procs.append(p)
            prefix = f"[{i}] " if args.tag_output else ""
            t = threading.Thread(target=_stream,
                                 args=(prefix, p.stdout, sys.stdout),
                                 daemon=True)
            t.start()
            threads.append(t)
        return procs, threads

    if args.elastic:
        rc = _supervise_elastic(args, _spawn_world)
    else:
        rc = _run_failfast(args, _spawn_world)
    if timeline_dir:
        # Collect + auto-merge the per-rank traces (whatever landed on
        # disk — the truncation-tolerant reader handles ranks that died
        # mid-write). Best-effort: a merge failure must not change the
        # job's exit code.
        try:
            from horovod_tpu.utils import trace as trace_mod

            info = trace_mod.merge(timeline_dir)
            sys.stderr.write(
                f"[launcher] merged timeline: {info['files']} rank "
                f"file(s), {info['events']} events -> {info['path']}\n")
        except Exception as exc:
            sys.stderr.write(f"[launcher] timeline merge failed: {exc}\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
