"""horovod_tpu — a TPU-native distributed training framework.

A ground-up re-design of the capabilities of Horovod v0.15.2
(reference: kuroko1t/horovod) for TPUs: ranks are TPU chips in a
``jax.sharding.Mesh``, the data plane is XLA collectives over ICI/DCN
(not MPI/NCCL), and gradient reduction is compiled into the training
step rather than negotiated tensor-by-tensor at runtime.

Top-level API mirrors the reference's ``horovod.common`` basics
(reference: horovod/common/__init__.py:51-154) plus the shared
collective verbs. Framework frontends live in submodules:

- :mod:`horovod_tpu.jax`    — flagship frontend (reference: horovod/tensorflow)
- :mod:`horovod_tpu.torch`  — PyTorch frontend (reference: horovod/torch)
- :mod:`horovod_tpu.keras`  — flax/optax trainer + callbacks (reference: horovod/keras)
"""

__version__ = "0.1.0"

from horovod_tpu.common.topology import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_size,
    local_rank,
    local_num_processes,
    cross_size,
    cross_rank,
    num_processes,
    process_index,
    mesh,
    devices,
    device_rank_axis,
    is_homogeneous,
    mpi_threads_supported,
)
from horovod_tpu.ops.collectives import (  # noqa: F401
    allreduce,
    allgather,
    broadcast,
    reducescatter,
    alltoall,
    grouped_allreduce,
    allreduce_pytree,
    broadcast_pytree,
)
from horovod_tpu.core.telemetry import (  # noqa: F401
    telemetry,
    report as telemetry_report,
)
from horovod_tpu.core.numerics import (  # noqa: F401
    NonfiniteError,
    check_consistency,
    report as numerics_report,
)
from horovod_tpu.core.fleet import (  # noqa: F401
    fleet_report,
)
from horovod_tpu.core.doctor import (  # noqa: F401
    diagnose,
)
