"""Collective ops — the XLA data plane (reference: horovod/common/operations.cc
PerformOperation, :735-1531, re-designed as compiled SPMD collectives)."""

from horovod_tpu.ops.collectives import (  # noqa: F401
    HVD_AXIS,
    axis_rank,
    in_spmd,
    allreduce,
    allgather,
    broadcast,
    reducescatter,
    alltoall,
    grouped_allreduce,
    allreduce_pytree,
    broadcast_pytree,
    ranked_allreduce,
    ranked_allgather,
    ranked_broadcast,
    ranked_reducescatter,
    ranked_alltoall,
)
