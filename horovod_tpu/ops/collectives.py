"""TPU-native collectives.

Re-design of the reference's collective execution engine
(reference: horovod/common/operations.cc:735-1531 ``PerformOperation``) as
compiled XLA collectives. There is no negotiation, no fusion buffer and no
background thread on this path: SPMD determinism makes the rank-0 coordinator
protocol (reference: operations.cc:279-517) unnecessary, and XLA fuses and
schedules collectives at compile time. The async host-side engine (for the
torch frontend) lives in :mod:`horovod_tpu.core` instead.

Two calling contexts:

1. **Inside SPMD code** (under ``shard_map``/``hvd.jit`` with the ``'hvd'``
   mesh axis bound): ``allreduce`` lowers to ``lax.psum`` over ICI — this is
   the hot path that replaces ``MPI_Allreduce``/``ncclAllReduce``.
2. **Eager host calls**: the value on this controller is the contribution of
   each of its local chips; a cached jitted ``shard_map`` program runs the
   collective across the whole mesh. Matches the reference's semantics where
   every rank contributes a tensor (reference: horovod/tensorflow/mpi_ops.py).

``ranked_*`` variants take an explicitly stacked per-rank array (leading axis
= world size, sharded over the mesh); they are the primitive everything else
is built on, and what tests use to express distinct per-rank values on one
controller.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.common import topology as _topo
from horovod_tpu.common.topology import HVD_AXIS

from horovod_tpu.common.compat import shard_map as _shard_map
from horovod_tpu.core import numerics as _num
from horovod_tpu.core import telemetry as _tele


# Two-tier axis names, matching horovod_tpu.parallel.mesh (not imported:
# the parallel package pulls flax; these two literals are the contract).
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def hierarchical_allreduce_enabled() -> bool:
    """HVD_HIERARCHICAL_ALLREDUCE routes rank-axis allreduces through
    reduce-scatter(ICI) -> psum(DCN) -> all-gather(ICI) whenever the world
    has a two-tier mesh (reference: HOROVOD_HIERARCHICAL_ALLREDUCE,
    operations.cc:1760-1778, composition :1194-1346)."""
    v = (os.environ.get("HVD_HIERARCHICAL_ALLREDUCE")
         or os.environ.get("HOROVOD_HIERARCHICAL_ALLREDUCE") or "")
    return v.lower() not in ("", "0", "false", "off")


def hierarchical_allgather_enabled() -> bool:
    """HVD_HIERARCHICAL_ALLGATHER: two-phase allgather (reference:
    HOROVOD_HIERARCHICAL_ALLGATHER shared-memory path,
    operations.cc:875-1010)."""
    v = (os.environ.get("HVD_HIERARCHICAL_ALLGATHER")
         or os.environ.get("HOROVOD_HIERARCHICAL_ALLGATHER") or "")
    return v.lower() not in ("", "0", "false", "off")


def _hier_allreduce_active() -> bool:
    st = _topo._require_init()
    return hierarchical_allreduce_enabled() and st.two_tier is not None


def _hier_allgather_active() -> bool:
    st = _topo._require_init()
    return hierarchical_allgather_enabled() and st.two_tier is not None


# ---------------------------------------------------------------------------
# SPMD-context helpers
# ---------------------------------------------------------------------------

def _name_bound(name: str) -> bool:
    try:
        lax.axis_index(name)
        return True
    except NameError:
        return False


def rank_axes():
    """The mesh axis name(s) enumerating ranks in the current SPMD context:
    ``'hvd'`` over the flat world mesh, ``('dcn', 'ici')`` over the
    two-tier mesh (hvd.jax.jit under HVD_HIERARCHICAL_ALLREDUCE). None
    outside any rank axis."""
    if _name_bound(HVD_AXIS):
        return HVD_AXIS
    if _name_bound(DCN_AXIS) and _name_bound(ICI_AXIS):
        return (DCN_AXIS, ICI_AXIS)
    return None


def axis_rank():
    """Per-chip rank inside SPMD code (the in-program analogue of
    ``hvd.rank()``; reference rank discovery: operations.cc:1664-1666)."""
    ax = rank_axes()
    if ax is None:
        _require_axis("axis_rank")
    return lax.axis_index(ax)


def in_spmd(x=None) -> bool:
    """True when called from inside a traced program (where collectives must
    lower to lax primitives rather than launch an eager program)."""
    if x is not None and isinstance(x, jax.core.Tracer):
        return True
    return False


def _require_axis(opname: str):
    """Raise a clear error when a collective is traced without a rank axis
    (e.g. plain ``jax.jit`` instead of ``hvd.jit``/``shard_map``)."""
    raise RuntimeError(
        f"horovod_tpu.{opname} was traced without the '{HVD_AXIS}' mesh axis "
        f"(or the '{DCN_AXIS}'/'{ICI_AXIS}' pair). Wrap your step with "
        "horovod_tpu.jax.jit(...) / shard_map over the world mesh, or call "
        "it eagerly on concrete arrays."
    )


# ---------------------------------------------------------------------------
# Ranked primitives: stacked per-rank arrays over the device mesh
# ---------------------------------------------------------------------------

def _psum_avg(x, world: int, average: bool, axis=HVD_AXIS):
    """psum, optionally averaged, preserving integer dtypes (floor-divide)
    so traced and eager calls agree."""
    r = lax.psum(x, axis)
    if average:
        if jnp.issubdtype(x.dtype, jnp.floating) or jnp.issubdtype(x.dtype, jnp.complexfloating):
            r = (r / world).astype(x.dtype)
        else:
            r = r // world
    return r


def _hier_allreduce(x, average: bool, dcn_policy=None):
    """reduce-scatter(ICI) -> psum(DCN) -> all-gather(ICI) over the bound
    two-tier axes; the lazy import keeps flax off the hot import path.
    ``dcn_policy`` (quantized compression policy) swaps the DCN psum for
    the block-scaled wire exchange of the 1/L shard."""
    from horovod_tpu.parallel.hierarchical import hierarchical_allreduce

    return hierarchical_allreduce(x, ICI_AXIS, DCN_AXIS, average=average,
                                  dcn_policy=dcn_policy)


def dcn_wire_policy(dcn_wire):
    """Resolve a per-tier DCN wire-policy NAME (the engine vocabulary:
    'none'/'int8'/'fp8') to the quantized compression policy object that
    drives the hierarchical DCN phase; 'none'/None -> None. Non-quantized
    spellings fail fast — the DCN tier-wire is the EQuARX block-scaled
    pipeline, not a cast."""
    if not dcn_wire or dcn_wire == "none":
        return None
    from horovod_tpu.jax.compression import Compression

    pol = Compression.resolve(dcn_wire, where="dcn_wire")
    if not getattr(pol, "quantized", False):
        raise ValueError(
            f"dcn_wire={dcn_wire!r} is not a quantized wire policy: the "
            "hierarchical DCN phase ships the block-scaled payload+scales "
            "format ('int8' or 'fp8')")
    return pol


def _spmd_allreduce(x, average: bool, ax):
    """In-SPMD allreduce over whatever rank axes are bound, hierarchical
    when the two-tier axes are available and the env knob is on."""
    if lax.psum(1, ax) == 1:
        return x  # single-rank axis: sum and mean are both identity
    if isinstance(ax, tuple) and hierarchical_allreduce_enabled():
        return _hier_allreduce(x, average)
    return _psum_avg(x, lax.psum(1, ax), average, axis=ax)


def _root_select_psum(x, root: int, axis=HVD_AXIS):
    """Broadcast-from-root as select + psum. The select (not a mask multiply)
    keeps NaN/Inf on non-root ranks from poisoning the sum; bools ride
    through an integer cast since psum is undefined for them."""
    idx = lax.axis_index(axis)
    asbool = x.dtype == jnp.bool_
    v = x.astype(jnp.int8) if asbool else x
    v = jnp.where(idx == root, v, jnp.zeros_like(v))
    r = lax.psum(v, axis)
    return r.astype(jnp.bool_) if asbool else r


def _mesh():
    return _topo._require_init().mesh


def _rank_sharding(mesh, ndim: int):
    return NamedSharding(mesh, P(HVD_AXIS, *([None] * (ndim - 1))))


@functools.lru_cache(maxsize=None)
def _ranked_program(op: str, mesh_key, root: int, average: bool,
                    hier: bool = False, dcn_wire: str = "none"):
    """Build + cache a jitted collective over the current mesh. jit itself
    caches per shape/dtype, so one program object serves all tensors.

    ``hier=True`` builds the program over the (dcn, ici) two-tier mesh
    with the hierarchical composition (reference: operations.cc:1194-1346,
    875-1010) instead of the flat world mesh — rank identity is unchanged
    because the two meshes hold the same devices in the same order
    (topology._build_two_tier enforces it). ``dcn_wire`` (hier allreduce
    only) quantizes the cross-tier phase: the ICI reduce-scatter stays at
    the resident dtype and only the 1/L shard crosses DCN block-scaled."""
    st = _topo._require_init()
    mesh = st.two_tier if hier else st.mesh
    world = mesh.devices.size
    rank_spec = (DCN_AXIS, ICI_AXIS) if hier else HVD_AXIS
    dcn_pol = dcn_wire_policy(dcn_wire) if hier else None

    def body(stacked):
        # stacked: local shard of the (size, *shape) array => (1, *shape);
        # x is this rank's tensor.
        x = stacked[0]
        if op == "allreduce":
            if hier:
                pol = (dcn_pol if jnp.issubdtype(x.dtype, jnp.floating)
                       else None)
                return _hier_allreduce(x, average, pol)
            return _psum_avg(x, world, average)
        if op == "allgather":
            if hier:
                from horovod_tpu.parallel.hierarchical import (
                    hierarchical_allgather,
                )

                return hierarchical_allgather(x, ICI_AXIS, DCN_AXIS)
            return lax.all_gather(x, HVD_AXIS, axis=0, tiled=True)
        if op == "broadcast":
            return _root_select_psum(x, root, axis=rank_spec)
        if op == "reducescatter":
            return lax.psum_scatter(_pad_dim0(x, world), rank_spec,
                                    scatter_dimension=0, tiled=True)[None]
        if op == "alltoall":
            return lax.all_to_all(x, rank_spec, split_axis=0, concat_axis=0, tiled=True)[None]
        raise ValueError(op)

    if op in ("allreduce", "allgather", "broadcast"):
        out_spec = P()  # replicated result on every rank
    else:
        out_spec = P(rank_spec)  # per-rank results, stacked

    def run(stacked):
        spec = P(rank_spec, *([None] * (stacked.ndim - 1)))
        # check_vma=False: all_gather/all_to_all results are replicated or
        # per-rank by construction; jax's static replication checker cannot
        # infer this for every primitive.
        return _shard_map(
            body, mesh=mesh, in_specs=spec, out_specs=out_spec, check_vma=False
        )(stacked)

    return jax.jit(run)


def _mesh_key():
    st = _topo._require_init()
    return (id(st.mesh), st.size)


def make_ranked(per_rank_values: Sequence[jnp.ndarray]):
    """Assemble a stacked (size, ...) array from one value per rank, sharded
    so rank r's value lives on chip r. Test/debug utility."""
    st = _topo._require_init()
    vals = [jnp.asarray(v) for v in per_rank_values]
    if len(vals) != st.size:
        raise ValueError(f"expected {st.size} values, got {len(vals)}")
    shape = (st.size,) + vals[0].shape
    sharding = _rank_sharding(st.mesh, len(shape))
    shards = [
        jax.device_put(v[None], d) for v, d in zip(vals, st.devices)
        if d in st.local_devices
    ]
    return jax.make_array_from_single_device_arrays(shape, sharding, shards)


def _local_row(stacked_out):
    """Fetch this process's first rank's row of a P('hvd')-sharded result.
    Plain indexing would fail on non-fully-addressable arrays in
    multi-process runs; the local shard is always addressable."""
    st = _topo._require_init()
    d0 = st.local_devices[0]
    for shard in stacked_out.addressable_shards:
        if shard.device == d0:
            return jnp.asarray(shard.data)[0]
    raise RuntimeError("no addressable shard on this process's first device")


def _replicated_stack(x):
    """Stack this controller's value as the contribution of each of its local
    chips (the eager-call data layout)."""
    st = _topo._require_init()
    x = jnp.asarray(x)
    shape = (st.size,) + x.shape
    sharding = _rank_sharding(st.mesh, len(shape))
    shards = [jax.device_put(x[None], d) for d in st.local_devices]
    return jax.make_array_from_single_device_arrays(shape, sharding, shards)


def ranked_allreduce(stacked, average: bool = False,
                     dcn_wire: str = "none"):
    """Sum (or mean) of per-rank tensors; result replicated to all ranks.
    Routed hierarchically (ICI/DCN split) when HVD_HIERARCHICAL_ALLREDUCE
    is on and the world has a two-tier mesh; ``dcn_wire`` then quantizes
    the cross-tier phase (ignored on the flat route — there is no DCN
    hop to shrink)."""
    hier = _hier_allreduce_active()
    return _ranked_program("allreduce", _mesh_key(), 0, average,
                           hier=hier,
                           dcn_wire=dcn_wire if hier else "none")(stacked)


def ranked_allgather(stacked):
    """Concatenate per-rank tensors along dim 0 (reference: MPI_Allgatherv
    path, operations.cc:810-857); result (size*n, ...) replicated."""
    return _ranked_program("allgather", _mesh_key(), 0, False,
                           hier=_hier_allgather_active())(stacked)


def _check_root(root_rank: int) -> int:
    """Validate root range like the coordinator's response validation
    (reference: operations.cc:315-517 surfaces ERROR for bad requests)."""
    st = _topo._require_init()
    root_rank = int(root_rank)
    if not 0 <= root_rank < st.size:
        raise ValueError(
            f"root_rank {root_rank} is out of range for world size {st.size}"
        )
    return root_rank


def ranked_broadcast(stacked, root_rank: int):
    """Every rank receives rank ``root_rank``'s tensor."""
    return _ranked_program("broadcast", _mesh_key(), _check_root(root_rank), False)(stacked)


def ranked_reducescatter(stacked):
    """Rank r receives the r-th 1/size chunk (dim 0) of the rank-sum.
    Result stacked: (size, n/size, ...)."""
    return _ranked_program("reducescatter", _mesh_key(), 0, False)(stacked)


def ranked_alltoall(stacked):
    """Rank r sends its j-th chunk to rank j. Result stacked (size, n, ...)
    where row r is the concat of chunks received by rank r."""
    return _ranked_program("alltoall", _mesh_key(), 0, False)(stacked)


# ---------------------------------------------------------------------------
# Consistency-check mode (debug): reproduce the reference coordinator's
# request validation (operations.cc:315-517). SPMD determinism makes this
# structurally unnecessary, but when hunting divergence bugs across
# controller processes, HVD_CONSISTENCY_CHECKS=1 cross-checks every eager
# collective's (op, dtype, shape, root) before executing it and surfaces
# mismatches as errors on EVERY process, like the broadcast ERROR response.
# ---------------------------------------------------------------------------

_FP_LEN = 16  # op, root, dtype-hash, ndim, dims[<=11], flags


def consistency_checks_enabled() -> bool:
    """NOTE: the flag must be set uniformly on EVERY controller process —
    the check itself is a collective, so partial enablement desynchronizes
    the launch order (a hang, not an error). '0'/'false'/'off' disable."""
    val = (os.environ.get("HVD_CONSISTENCY_CHECKS")
           or os.environ.get("HOROVOD_CONSISTENCY_CHECKS") or "")
    return val.lower() not in ("", "0", "false", "off")


def _maybe_consistency_check(op_code: int, tensor, root: int = -1,
                             flags: int = 0):
    st = _topo._require_init()
    if not consistency_checks_enabled() or st.num_processes == 1:
        return
    fp = np.zeros((_FP_LEN,), np.int32)
    fp[0] = op_code
    fp[1] = root
    import zlib

    # crc32, not hash(): Python string hashing is salted per process.
    fp[2] = zlib.crc32(str(jnp.asarray(tensor).dtype).encode()) % (2 ** 31)
    shape = jnp.asarray(tensor).shape
    fp[3] = len(shape)
    for i, d in enumerate(shape[:11]):
        fp[4 + i] = d % (2 ** 31)
    fp[15] = flags  # e.g. the allreduce average flag
    # Every local chip contributes this controller's fingerprint; the
    # gathered matrix is identical everywhere, so the error (or not) is
    # raised consistently on every process.
    gathered = np.asarray(ranked_allgather(_replicated_stack(jnp.asarray(fp))))
    gathered = gathered.reshape(st.size, _FP_LEN)
    if not (gathered == gathered[0]).all():
        bad = np.where((gathered != gathered[0]).any(axis=1))[0]
        raise _topo.HorovodInternalError(
            f"consistency check failed: ranks {bad.tolist()} submitted a "
            f"mismatched collective (op/dtype/shape/root fingerprints "
            f"differ; local fingerprint {fp.tolist()}). The reference "
            "coordinator would return an ERROR response here "
            "(operations.cc:315-517).")


# ---------------------------------------------------------------------------
# Public verbs — context-polymorphic (SPMD tracer or eager host value)
# ---------------------------------------------------------------------------

def _nbytes(tensor) -> int:
    """Host-visible byte size of an eager tensor (telemetry accounting)."""
    try:
        return int(np.prod(tensor.shape) if tensor.shape else 1) \
            * np.dtype(tensor.dtype).itemsize
    except Exception:
        return 0


def _record_eager(op: str, tensor, elided: bool = False):
    """Feed the telemetry registry for one eager collective. The compiled
    (SPMD) path deliberately records nothing here — tracing happens once,
    and its cost story lives in the xplane capture instead.

    Under the numerics policy (core/numerics.py) a HOST-resident eager
    input is also scanned for nonfinite values — eager collectives are
    control-plane traffic (metric averaging, state broadcasts), exactly
    where a NaN silently spreads to every rank. Device-resident inputs
    are deliberately NOT scanned: np.asarray on them would force a
    blocking device→host fetch per tensor inside the drain window
    CLAUDE.md flags as rendezvous-sensitive (the compiled-path health
    and the engine submit hooks cover those buffers without extra
    transfers). Counter only, no verdict: the collective itself may be
    the legitimate carrier (a broadcast of a diverged peer's state for
    inspection), and MetricAverage has its own masking."""
    _tele.record_eager(op, _nbytes(tensor), elided=elided)
    if _num.enabled() and isinstance(tensor, np.ndarray):
        _num.note_eager_nonfinite(op, _num.np_nonfinite(tensor))


def _localize(x):
    """Re-home an eager collective's replicated GLOBAL output as an
    ordinary process-local array. In a multi-controller world the raw
    output is committed to the whole device set; feeding it to any
    subsequent local eager op fails jax's addressability checks (a
    reference user never sees this — each mpirun rank only ever holds
    local tensors). The local shard of a replicated result holds the full
    value, so one host hop restores composability. Single-controller runs
    return the array untouched."""
    st = _topo._require_init()
    if st.num_processes == 1:
        return x
    return jnp.asarray(np.asarray(x))


def fetch(x) -> np.ndarray:
    """Device→host of a possibly multi-process-sharded global array: the
    full global value on every process. Replicated/addressable arrays
    fetch directly; cross-process-sharded ones go through an allgather
    (``multihost_utils.process_allgather``)."""
    try:
        return np.asarray(x)
    except RuntimeError:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              dcn_wire: str = "none"):
    """Allreduce (reference API: horovod/tensorflow/mpi_ops.py:78-91 and
    horovod/common/operations.cc:1401-1496).

    Inside SPMD code this is ``lax.pmean``/``lax.psum`` over the chip mesh
    axis. Eagerly, every local chip contributes this controller's value.
    ``name`` is accepted for reference-API parity (negotiation needed names;
    SPMD ordering does not) and used by the timeline. ``dcn_wire`` (eager
    path) quantizes the cross-tier phase of a hierarchically-routed call —
    the engines' two-phase chunk route rides this.
    """
    if in_spmd(tensor):
        ax = rank_axes()
        if ax is None:
            _require_axis("allreduce")
        return _spmd_allreduce(tensor, average, ax)
    tensor = jnp.asarray(tensor)
    if _topo._require_init().size == 1:
        # identity — no program launch for a 1-rank world
        _record_eager("allreduce", tensor, elided=True)
        return tensor
    _record_eager("allreduce", tensor)
    _maybe_consistency_check(0, tensor, flags=int(average))
    return _localize(ranked_allreduce(_replicated_stack(tensor),
                                      average=average, dcn_wire=dcn_wire))


def allgather(tensor, name: Optional[str] = None):
    """Concatenation of every rank's tensor along dim 0 (reference:
    horovod/tensorflow/mpi_ops.py:108-126). Ranks may have different first
    dims; eagerly that can only differ across processes, handled by a size
    exchange + pad + strip (XLA collectives need static shapes)."""
    if in_spmd(tensor):
        ax = rank_axes()
        if ax is None:
            _require_axis("allgather")
        if lax.psum(1, ax) == 1:
            return tensor
        return lax.all_gather(tensor, ax, axis=0, tiled=True)
    tensor = jnp.asarray(tensor)
    if tensor.ndim == 0:
        raise ValueError("allgather requires a tensor with at least one dimension")
    if _topo._require_init().size == 1:
        _record_eager("allgather", tensor, elided=True)
        return tensor
    _record_eager("allgather", tensor)
    # Allgather legitimately permits differing first dims; check the rest.
    _maybe_consistency_check(1, tensor[:0])
    st = _topo._require_init()
    if st.num_processes == 1:
        return ranked_allgather(_replicated_stack(tensor))  # already local
    # Cross-process variable first dim: exchange per-rank sizes (each local
    # chip one-hots its own global rank), pad to the max, gather, strip.
    n = tensor.shape[0]
    shards = []
    for d in st.local_devices:
        # Use the device's true global rank: init(devices=...) permits
        # non-contiguous local blocks.
        onehot = jnp.zeros((st.size,), jnp.int32).at[st.devices.index(d)].set(n)
        shards.append(jax.device_put(onehot[None], d))
    stacked = jax.make_array_from_single_device_arrays(
        (st.size, st.size), _rank_sharding(st.mesh, 2), shards
    )
    sizes = np.asarray(ranked_allreduce(stacked))
    maxn = int(sizes.max())
    pad = [(0, maxn - n)] + [(0, 0)] * (tensor.ndim - 1)
    padded = jnp.pad(tensor, pad)
    gathered = np.asarray(ranked_allgather(_replicated_stack(padded)))
    gathered = gathered.reshape((st.size, maxn) + tensor.shape[1:])
    pieces = [gathered[r, : int(sizes[r])] for r in range(st.size)]
    return jnp.asarray(np.concatenate(pieces, axis=0))


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Every rank receives rank ``root_rank``'s value (reference:
    horovod/tensorflow/mpi_ops.py:151-167, operations.cc:1502-1522)."""
    root_rank = _check_root(root_rank)
    if in_spmd(tensor):
        ax = rank_axes()
        if ax is None:
            _require_axis("broadcast")
        if lax.psum(1, ax) == 1:
            return tensor
        return _root_select_psum(tensor, root_rank, axis=ax)
    tensor = jnp.asarray(tensor)
    if _topo._require_init().size == 1:
        _record_eager("broadcast", tensor, elided=True)
        return tensor
    _record_eager("broadcast", tensor)
    _maybe_consistency_check(2, tensor, root_rank)
    return _localize(ranked_broadcast(_replicated_stack(tensor), root_rank))


def _pad_dim0(tensor, multiple: int):
    """Zero-pad dim 0 up to the next multiple (the reducescatter padding
    contract); identity when already divisible."""
    rem = tensor.shape[0] % multiple
    if rem == 0:
        return tensor
    pad = [(0, multiple - rem)] + [(0, 0)] * (tensor.ndim - 1)
    return jnp.pad(tensor, pad)


def reducescatter(tensor, name: Optional[str] = None):
    """Sum over ranks, scattered: rank r keeps the r-th chunk of dim 0.
    (Beyond the reference's three verbs; native on TPU, and the building
    block of hierarchical allreduce — operations.cc:1194-1346.)

    Padding contract: a dim 0 not divisible by the world size is
    zero-padded to the next multiple, so rank r receives rows
    ``[r*c, (r+1)*c)`` of the padded sum where ``c = ceil(n/size)`` —
    the trailing ``size*c - n`` rows of rank ``size-1``'s chunk are
    zeros. A following tiled ``allgather`` returns the ``size*c``-row
    concatenation; slice ``[:n]`` to recover the original extent (this
    round trip is how the sharded weight update composes —
    horovod_tpu/jax/sharded.py)."""
    if in_spmd(tensor):
        ax = rank_axes()
        if ax is None:
            _require_axis("reducescatter")
        if tensor.ndim == 0:
            raise ValueError(
                "reducescatter requires a tensor with at least one dimension")
        world = lax.psum(1, ax)
        if world == 1:
            return tensor
        return lax.psum_scatter(_pad_dim0(tensor, world), ax,
                                scatter_dimension=0, tiled=True)
    tensor = jnp.asarray(tensor)
    if tensor.ndim == 0:
        raise ValueError(
            "reducescatter requires a tensor with at least one dimension")
    if _topo._require_init().size == 1:
        _record_eager("reducescatter", tensor, elided=True)
        return tensor
    _record_eager("reducescatter", tensor)
    _maybe_consistency_check(3, tensor)
    # _local_row is already process-local — no _localize round trip.
    return _local_row(ranked_reducescatter(_replicated_stack(tensor)))


def alltoall(tensor, name: Optional[str] = None):
    """Each rank scatters equal chunks of dim 0 to all ranks and concatenates
    what it receives (beyond the reference's verbs; rides ICI natively)."""
    if in_spmd(tensor):
        ax = rank_axes()
        if ax is None:
            _require_axis("alltoall")
        if lax.psum(1, ax) == 1:
            return tensor
        return lax.all_to_all(tensor, ax, split_axis=0, concat_axis=0, tiled=True)
    tensor = jnp.asarray(tensor)
    if _topo._require_init().size == 1:
        _record_eager("alltoall", tensor, elided=True)
        return tensor
    _record_eager("alltoall", tensor)
    _maybe_consistency_check(4, tensor)
    return _local_row(ranked_alltoall(_replicated_stack(tensor)))


# ---------------------------------------------------------------------------
# Fusion: grouped collectives (reference: tensor fusion, C5 —
# fusion_buffer_manager.cc + operations.cc:2035-2074 — done at trace time)
# ---------------------------------------------------------------------------

def _flatten_group(tensors):
    shapes = [t.shape for t in tensors]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = jnp.concatenate([jnp.ravel(t) for t in tensors]) if tensors else jnp.zeros((0,))
    return flat, shapes, sizes


def _unflatten_group(flat, shapes, sizes):
    out, off = [], 0
    for shp, n in zip(shapes, sizes):
        out.append(jnp.reshape(flat[off : off + n], shp))
        off += n
    return out


def _grouped_apply(fn, tensors: Sequence):
    """Apply ``fn(flat_1d) -> flat_1d`` to tensors fused per dtype group —
    the fusion rule admits same-dtype responses only (reference:
    operations.cc:2049-2054), order preserved within each group."""
    tensors = [jnp.asarray(t) for t in tensors]
    if not tensors:
        return []
    by_dtype = {}
    for i, t in enumerate(tensors):
        by_dtype.setdefault(t.dtype, []).append(i)
    results = [None] * len(tensors)
    for idxs in by_dtype.values():
        group = [tensors[i] for i in idxs]
        flat, shapes, sizes = _flatten_group(group)
        out = fn(flat)
        for i, r in zip(idxs, _unflatten_group(out, shapes, sizes)):
            results[i] = r
    return results


def grouped_allreduce(tensors: Sequence, average: bool = True):
    """Allreduce many tensors as one fused buffer — the compile-time
    equivalent of the reference's 64 MB fusion buffer (reference:
    operations.cc:2035-2074, fusion_buffer_manager.cc). One collective per
    dtype group instead of one per tensor.

    World size 1 short-circuits BEFORE the packing: the concatenate ->
    all-reduce -> slice chain survives XLA simplification even with one
    participant, costing a full extra HBM round trip of the tensor set
    per step (measured on the one-chip bench — docs/benchmarks.md)."""
    if _topo._require_init().size == 1:
        out = [jnp.asarray(t) for t in tensors]
        for t in out:
            if not in_spmd(t):  # tracers: trace-time, not a per-step event
                _record_eager("allreduce", t, elided=True)
        return out
    return _grouped_apply(lambda flat: allreduce(flat, average=average), tensors)


def allreduce_pytree(tree, average: bool = True):
    """Fused allreduce over every leaf of a pytree (grad pytrees, metrics)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return jax.tree_util.tree_unflatten(treedef, grouped_allreduce(leaves, average))


def broadcast_pytree(tree, root_rank: int = 0):
    """Broadcast every leaf from ``root_rank`` (reference:
    broadcast_global_variables / broadcast_parameters — §3.4). Fused into
    one collective per dtype."""
    if _topo._require_init().size == 1:
        _check_root(root_rank)
        for leaf in jax.tree_util.tree_leaves(tree):
            # No jnp.asarray here: counting bytes must not device-put the
            # whole host-side tree on the very path that elides the
            # transfer. _nbytes reads shape/dtype only (0 for plain
            # python scalars — an acceptable undercount).
            if not in_spmd(leaf):  # tracers: trace-time, not per-step
                _record_eager("broadcast", leaf, elided=True)
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = _grouped_apply(lambda flat: broadcast(flat, root_rank), leaves)
    return jax.tree_util.tree_unflatten(treedef, out)
