"""Chunked softmax cross-entropy over a large vocabulary — the LM-head
loss without materializing the logits.

Motivation (measured, docs/benchmarks.md): for BERT-base at bs8/seq512 the
``[4096, 30522]`` f32 logits tensor is ~500 MB; the stock
``lm_head -> optax.softmax_cross_entropy_with_integer_labels`` path
writes it, re-reads it for logsumexp + label gather, and materializes its
gradient again in the backward — several GB of HBM traffic per step on a
bandwidth-bound chip (~22% of the whole training step). The reference has
no transformer, but the same idea is its fp16-compression playbook (C11):
spend FLOPs to move fewer bytes.

This op streams the vocabulary in chunks with an online logsumexp —
structurally the flash-attention trick (ops/flash_attention.py) applied to
the classifier head: the forward keeps only ``logsumexp`` and the label's
logit per token; the backward recomputes each chunk's logits, forms
``softmax - onehot`` on the fly, and accumulates dx / dW / db. Peak live
memory for the head drops from O(N*V) to O(N*chunk).

Measured on a v5e (docs/benchmarks.md "LM-head loss"): *throughput* is
parity-class with the stock path (XLA's own fusion of the head is
excellent; the backward's logits recompute costs the MXU what the
skipped HBM round-trips save) — slightly ahead at large batch×vocab,
slightly behind at BERT-base bs8. The wins are the O(N·chunk) memory
cap (vocab- and batch-scaling headroom the stock path lacks) and the
head staying off the remat path.

API mirrors ``optax.softmax_cross_entropy_with_integer_labels`` but takes
the head weights explicitly (they never produce logits in HBM):

    losses = chunked_softmax_cross_entropy(hidden, kernel, bias, labels)
    loss = losses.mean()

Matmuls run with bf16 operands and f32 accumulation
(``preferred_element_type``) — full MXU rate, stable f32 logsumexp.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

DEFAULT_CHUNK = 2048


def _chunk_logits(h2d, kernel, bias, c0, width):
    """One chunk's logits in f32: (h2d @ kernel[:, c0:c0+width]) + bias.
    bf16 operands, f32 accumulation."""
    kc = jax.lax.dynamic_slice_in_dim(kernel, c0, width, axis=1)
    bc = jax.lax.dynamic_slice_in_dim(bias, c0, width, axis=0)
    logits = jax.lax.dot_general(
        h2d, kc.astype(h2d.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return logits + bc.astype(jnp.float32)[None, :]


def _pad_vocab(kernel, bias, chunk):
    """Pad V up to a chunk multiple. Padded bias is -inf-like so the ghost
    columns vanish from logsumexp; labels never point at them."""
    v = kernel.shape[1]
    pad = (-v) % chunk
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        bias = jnp.pad(bias, (0, pad), constant_values=-1e30)
    return kernel, bias, v + pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def chunked_softmax_cross_entropy(hidden, kernel, bias, labels,
                                  chunk: int = DEFAULT_CHUNK):
    """Per-token losses ``logsumexp(h@W+b) - (h@W+b)[label]``.

    hidden: [..., H] (any leading shape; bf16 or f32)
    kernel: [H, V], bias: [V] — the head parameters
    labels: [...] int32, same leading shape as hidden.
      Precondition: ``0 <= label < V`` for every position. An
      out-of-range label (e.g. a -100 ignore-index) is NOT detected:
      its label-logit carry stays 0, the loss silently degrades to
      ``lse - 0``, and the backward emits a pure-softmax gradient.
      Mask ignored positions via the cotangent instead — clip their
      labels into range and weight the returned per-token losses with 0
      (that zero flows through ``g`` in the backward, zeroing their
      gradient); ``tests/test_chunked_loss.py::
      test_mask_ignored_labels_via_cotangent`` pins the convention.
    Returns f32 losses with the leading shape.
    """
    losses, _ = _fwd(hidden, kernel, bias, labels, chunk)
    return losses


def _fwd(hidden, kernel, bias, labels, chunk):
    lead = hidden.shape[:-1]
    h2d = hidden.reshape(-1, hidden.shape[-1])
    lab = labels.reshape(-1)
    n = h2d.shape[0]
    kernel_p, bias_p, vpad = _pad_vocab(kernel, bias, chunk)
    nchunks = vpad // chunk

    def body(carry, idx):
        m, s, lbl = carry
        c0 = idx * chunk
        logits = _chunk_logits(h2d, kernel_p, bias_p, c0, chunk)
        cmax = logits.max(axis=1)
        new_m = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - new_m) + jnp.exp(
            logits - new_m[:, None]).sum(axis=1)
        local = lab - c0
        inside = (local >= 0) & (local < chunk)
        safe = jnp.clip(local, 0, chunk - 1)
        got = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
        lbl = jnp.where(inside, got, lbl)
        return (new_m, s, lbl), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, lbl), _ = jax.lax.scan(body, init, jnp.arange(nchunks))
    lse = jnp.log(s) + m
    losses = (lse - lbl).reshape(lead)
    return losses, (hidden, kernel, bias, labels, lse)


def _bwd(chunk, residuals, g):
    hidden, kernel, bias, labels, lse = residuals
    lead = hidden.shape[:-1]
    h2d = hidden.reshape(-1, hidden.shape[-1])
    lab = labels.reshape(-1)
    gflat = g.reshape(-1).astype(jnp.float32)
    kernel_p, bias_p, vpad = _pad_vocab(kernel, bias, chunk)
    nchunks = vpad // chunk
    hdim, v = kernel.shape

    def body(dx, idx):
        c0 = idx * chunk
        logits = _chunk_logits(h2d, kernel_p, bias_p, c0, chunk)
        probs = jnp.exp(logits - lse[:, None])
        local = lab - c0
        onehot = (local[:, None] ==
                  jnp.arange(chunk)[None, :]).astype(jnp.float32)
        dlog = (probs - onehot) * gflat[:, None]          # [N, chunk] f32
        kc = jax.lax.dynamic_slice_in_dim(kernel_p, c0, chunk, axis=1)
        dlog_b = dlog.astype(h2d.dtype)
        # dx accumulates across chunks (carry); dW/db stack per chunk.
        dx = dx + jax.lax.dot_general(
            dlog_b, kc.astype(h2d.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dkc = jax.lax.dot_general(
            h2d, dlog_b, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [H, chunk]
        return dx, (dkc, dlog.sum(axis=0))

    dx0 = jnp.zeros((h2d.shape[0], hdim), jnp.float32)
    dx, (dks, dbs) = jax.lax.scan(body, dx0, jnp.arange(nchunks))
    dkernel = jnp.moveaxis(dks, 0, 1).reshape(hdim, vpad)[:, :v]
    dbias = dbs.reshape(vpad)[:v]
    return (dx.astype(hidden.dtype).reshape(hidden.shape),
            dkernel.astype(kernel.dtype),
            dbias.astype(bias.dtype),
            None)


chunked_softmax_cross_entropy.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Pallas kernel version — the XLA scan above caps live memory but still
# round-trips each [N, chunk] logits tile through HBM (the two-pass
# max/exp reduction defeats single-kernel fusion). These kernels keep the
# tile in VMEM, flash-attention style (ops/flash_attention.py is the
# structural template; vocabulary columns play the role of keys).
# ---------------------------------------------------------------------------

import jax.experimental.pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

_STAT = 128  # lane width for (block_n, 128) row-stat scratch tiles


def _ce_fwd_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, lbl_ref,
                   m_ref, l_ref, acc_ref, *, nv: int, block_v: int):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    w = w_ref[...].astype(x.dtype)
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[...].astype(jnp.float32)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(jnp.exp(logits - m_new), axis=1,
                                     keepdims=True)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    local = lab_ref[...] - vi * block_v                     # (bn, 1)
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    picked = jnp.sum(jnp.where(col == local, logits, 0.0), axis=1,
                     keepdims=True)
    acc_ref[...] += jnp.broadcast_to(picked, acc_ref.shape)

    @pl.when(vi == nv - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe = jnp.where(l > 0, l, 1.0)
        lse_ref[...] = m_ref[:, :1] + jnp.log(safe)
        lbl_ref[...] = acc_ref[:, :1]


def _ce_dlog(x, w_ref, b_ref, lab_ref, lse_ref, g_ref, vi, block_v):
    """Recompute one tile's (softmax - onehot) * g from the row stats —
    shared by both backward kernels (the flash recurrence's `ds`)."""
    w = w_ref[...].astype(x.dtype)
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[...].astype(jnp.float32)
    p = jnp.exp(logits - lse_ref[...])                       # (bn, bv) f32
    local = lab_ref[...] - vi * block_v
    col = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    return (p - (col == local).astype(jnp.float32)) * g_ref[...], w


def _ce_dx_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, g_ref, dx_ref,
                  acc_ref, *, nv: int, block_v: int):
    # grid (nn, nv): vocab inner — dx accumulates in VMEM scratch.
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    dlog, w = _ce_dlog(x, w_ref, b_ref, lab_ref, lse_ref, g_ref, vi,
                       block_v)
    acc_ref[...] += jax.lax.dot_general(
        dlog.astype(x.dtype), w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vi == nv - 1)
    def _finalize():
        dx_ref[...] = acc_ref[...].astype(dx_ref.dtype)


def _ce_dw_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, g_ref,
                  dw_ref, db_ref, accw_ref, accb_ref,
                  *, nn: int, block_v: int):
    # grid (nv, nn): tokens inner — dW/db accumulate in VMEM scratch.
    vi = pl.program_id(0)
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        accw_ref[...] = jnp.zeros_like(accw_ref)
        accb_ref[...] = jnp.zeros_like(accb_ref)

    x = x_ref[...]
    dlog, _ = _ce_dlog(x, w_ref, b_ref, lab_ref, lse_ref, g_ref, vi,
                       block_v)
    accw_ref[...] += jax.lax.dot_general(
        x, dlog.astype(x.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    accb_ref[...] += jnp.broadcast_to(
        jnp.sum(dlog, axis=0, keepdims=True), accb_ref.shape)

    @pl.when(ni == nn - 1)
    def _finalize():
        dw_ref[...] = accw_ref[...]
        db_ref[...] = accb_ref[:1, :]


def _pad_rows(a, mult, value=0):
    pad = (-a.shape[0]) % mult
    if pad:
        a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1),
                    constant_values=value)
    return a


@functools.partial(jax.jit,
                   static_argnames=("block_n", "block_v", "interpret"))
def _ce_fwd_call(h2d, kernel, bias, lab, block_n, block_v, interpret):
    n0, hdim = h2d.shape
    kernel_p, bias_p, vpad = _pad_vocab(kernel, bias, block_v)
    # Stream W in the compute dtype: an f32 W would double every kernel's
    # dominant HBM traffic (each token-block pass re-reads all of W).
    kernel_p = kernel_p.astype(h2d.dtype)
    x = _pad_rows(h2d, block_n)
    labs = _pad_rows(lab[:, None], block_n)
    n = x.shape[0]
    nn, nv = n // block_n, vpad // block_v
    lse, lbl = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, nv=nv, block_v=block_v),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((block_n, hdim), lambda i, j: (i, 0)),
            pl.BlockSpec((hdim, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_n, _STAT), jnp.float32),
                        pltpu.VMEM((block_n, _STAT), jnp.float32),
                        pltpu.VMEM((block_n, _STAT), jnp.float32)],
        interpret=interpret,
    )(x, kernel_p, bias_p[None, :], labs)
    return (lse[:n0, 0] - lbl[:n0, 0]), lse[:, 0]


@functools.partial(jax.jit,
                   static_argnames=("block_n", "block_v", "interpret"))
def _ce_bwd_call(h2d, kernel, bias, lab, lse, g, block_n, block_v,
                 interpret):
    n0, hdim = h2d.shape
    v = kernel.shape[1]
    kernel_p, bias_p, vpad = _pad_vocab(kernel, bias, block_v)
    kernel_p = kernel_p.astype(h2d.dtype)  # see _ce_fwd_call
    x = _pad_rows(h2d, block_n)
    labs = _pad_rows(lab[:, None], block_n)
    n = x.shape[0]
    # Padded rows carry g=0 => dlog rows vanish; their garbage lse is inert.
    gpad = _pad_rows(g.astype(jnp.float32)[:, None], block_n)
    lsep = _pad_rows(lse[:, None], block_n)
    nn, nv = n // block_n, vpad // block_v
    inputs = (x, kernel_p, bias_p[None, :], labs, lsep, gpad)
    # Two kernels, each with a clean VMEM accumulator over its inner grid
    # axis (the flash-attention dq/dkv split, ops/flash_attention.py:
    # _dq_kernel/_dkv_kernel): a cross-OUTER-axis accumulator would need
    # non-contiguous output-block revisits, which pallas does not give.
    n_specs = [
        pl.BlockSpec((block_n, hdim), lambda i, j: (i, 0)),
        pl.BlockSpec((hdim, block_v), lambda i, j: (0, j)),
        pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
        pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
    ]
    dx = pl.pallas_call(
        functools.partial(_ce_dx_kernel, nv=nv, block_v=block_v),
        grid=(nn, nv),
        in_specs=n_specs,
        # dx leaves in the compute dtype (the caller casts to
        # hidden.dtype anyway); the accumulator scratch stays f32.
        out_specs=pl.BlockSpec((block_n, hdim), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, hdim), h2d.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, hdim), jnp.float32)],
        interpret=interpret,
    )(*inputs)
    # The dW kernel carries three (hdim, vocab-tile) buffers — the
    # double-buffered W input, the f32 scratch accumulator, and the
    # double-buffered f32 dW output — so it runs a smaller token tile to
    # stay inside the 16 MB scoped-VMEM stack at full vocab-tile width.
    bn_dw = 256 if n % 256 == 0 and block_n > 256 else block_n
    nn_dw = n // bn_dw
    v_specs = [
        pl.BlockSpec((bn_dw, hdim), lambda j, i: (i, 0)),
        pl.BlockSpec((hdim, block_v), lambda j, i: (0, j)),
        pl.BlockSpec((1, block_v), lambda j, i: (0, j)),
        pl.BlockSpec((bn_dw, 1), lambda j, i: (i, 0)),
        pl.BlockSpec((bn_dw, 1), lambda j, i: (i, 0)),
        pl.BlockSpec((bn_dw, 1), lambda j, i: (i, 0)),
    ]
    dw, db = pl.pallas_call(
        functools.partial(_ce_dw_kernel, nn=nn_dw, block_v=block_v),
        grid=(nv, nn_dw),
        in_specs=v_specs,
        out_specs=[
            pl.BlockSpec((hdim, block_v), lambda j, i: (0, j)),
            pl.BlockSpec((1, block_v), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((hdim, vpad), jnp.float32),
            jax.ShapeDtypeStruct((1, vpad), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((hdim, block_v), jnp.float32),
                        pltpu.VMEM((8, block_v), jnp.float32)],
        interpret=interpret,
    )(*inputs)
    return dx[:n0], dw[:, :v], db[0, :v]


def _resolve_interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_softmax_cross_entropy(hidden, kernel, bias, labels,
                                block_n: int = 512, block_v: int = 1024,
                                interpret: bool | None = None):
    """Pallas-kernel LM-head loss: same contract as
    :func:`chunked_softmax_cross_entropy`, but the per-tile logits never
    leave VMEM in either direction. Off-TPU the kernels run in pallas
    interpret mode (tests/CPU)."""
    losses, _ = _fused_fwd_rule(hidden, kernel, bias, labels, block_n,
                                block_v, interpret)
    return losses


def _fused_fwd_rule(hidden, kernel, bias, labels, block_n, block_v,
                    interpret):
    lead = hidden.shape[:-1]
    h2d = hidden.reshape(-1, hidden.shape[-1])
    lab = labels.reshape(-1)
    losses, lse = _ce_fwd_call(h2d, kernel, bias, lab, block_n, block_v,
                               _resolve_interpret(interpret))
    return losses.reshape(lead), (hidden, kernel, bias, labels, lse)


def _fused_bwd_rule(block_n, block_v, interpret, residuals, g):
    hidden, kernel, bias, labels, lse = residuals
    h2d = hidden.reshape(-1, hidden.shape[-1])
    dx, dw, db = _ce_bwd_call(
        h2d, kernel, bias, labels.reshape(-1), lse, g.reshape(-1),
        block_n, block_v, _resolve_interpret(interpret))
    return (dx.astype(hidden.dtype).reshape(hidden.shape),
            dw.astype(kernel.dtype), db.astype(bias.dtype), None)


fused_softmax_cross_entropy.defvjp(_fused_fwd_rule, _fused_bwd_rule)
