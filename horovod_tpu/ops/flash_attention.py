"""Flash attention as a pallas TPU kernel — forward AND backward.

The hot op of transformer training. XLA's stock attention materializes the
(s × s) logits in HBM; this kernel streams one (block_q × d) Q tile and one
(block_k × d) K/V tile through VMEM per grid step with an online softmax,
so HBM traffic is O(s·d) instead of O(s²) and VMEM residency is bounded by
the block sizes regardless of sequence length — the standard flash
formulation (Dao et al.), written for the MXU: accumulation in f32, block
sizes default to 512 (a multiple of the 128-wide systolic tile — measured
~2× faster than 128-blocks on a v5e at s=2048-8192, and 2.6× faster than
the stock attention at s=4096 fwd+bwd).

Training works end-to-end: :func:`flash_attention` carries a
``jax.custom_vjp`` whose backward recomputes attention probabilities from
the saved log-sum-exp row statistics (no (s × s) residuals), with one
kernel producing dQ (grid over Q tiles, streaming K/V) and one producing
dK/dV (grid over K tiles, streaming Q), per the flash backward recurrence:

    p_ij = exp(q_i·k_j·scale − lse_i)
    dv_j = Σ_i p_ij · do_i
    ds_ij = p_ij · (do_i·v_j − Δ_i),   Δ_i = do_i·o_i
    dq_i = Σ_j ds_ij · k_j · scale
    dk_j = Σ_i ds_ij · q_i · scale

Plugs in anywhere the model zoo accepts an ``attention_fn``
(:class:`horovod_tpu.models.TransformerConfig`) and composes with sequence
parallelism: inside :func:`horovod_tpu.parallel.ulysses_attention` it
kernels the per-head full-sequence attention, and ring attention's
per-block math is the same online-softmax update this kernel runs locally.

Off-TPU (tests, CPU debugging) the kernels run in pallas interpret mode —
same code path, scalar semantics.

(Reference parity note: kuroko1t/horovod contains no attention ops — this
is TPU-native scope beyond the reference, serving its examples' model
families at scale.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_STAT = 128  # lane width for the (block_q, 128) row-stat scratch tiles


def _mask_block(sblk, qi, ki, block_q, block_k):
    """Causal mask for one (block_q, block_k) logits tile."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, sblk.shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, sblk.shape, 1)
    return jnp.where(q_pos >= k_pos, sblk, NEG_INF)


# ---------------------------------------------------------------------------
# Forward: grid (batch*heads, nq, nk) — K/V innermost so one K/V tile is
# resident at a time; output + lse written on the last K step from VMEM
# scratch accumulators.
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal: bool, scale: float, nk: int,
                block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    visible = (qi * block_q + block_q > ki * block_k) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        sblk = q @ kb.T  # (bq, bk) on the MXU
        if causal:
            sblk = _mask_block(sblk, qi, ki, block_q, block_k)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + p @ vb
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[...] / safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(safe)  # (bq, 1) lane


def _kv_index(causal: bool, block_q: int, block_k: int):
    """K/V index map for grids where the k tile is the innermost axis.
    For causal attention the index is clamped to the last visible k block
    of the current q block: pallas skips the HBM->VMEM copy when the
    block index repeats between grid steps, so fully-masked steps (whose
    compute pl.when also skips) cost no memory traffic."""
    if not causal:
        return lambda b, i, j: (b, j, 0)
    last = lambda i: (i * block_q + block_q - 1) // block_k  # noqa: E731
    return lambda b, i, j: (b, jnp.minimum(j, last(i)), 0)


def _q_index(causal: bool, block_q: int, block_k: int):
    """Q-side index map for the dK/dV grid (q tile innermost): clamped up
    to the first visible q block of the current k block (same
    repeated-index DMA-skip trick as _kv_index)."""
    if not causal:
        return lambda b, i, j: (b, j, 0)
    first = lambda i: (i * block_k) // block_q  # noqa: E731
    return lambda b, i, j: (b, jnp.maximum(j, first(i)), 0)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _fwd_bhsd(q, k, v, causal, block_q, block_k, interpret):
    bh, s, d = q.shape
    nq, nk = s // block_q, s // block_k
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=d ** -0.5,
                               nk=nk, block_q=block_q, block_k=block_k)
    kv_idx = _kv_index(causal, block_q, block_k)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # Row stats ride a trailing unit lane dim: Mosaic requires the
            # last two block dims be (8, 128)-divisible or array-equal.
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _STAT), jnp.float32),
            pltpu.VMEM((block_q, _STAT), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Backward: two kernels, both recomputing p from (q, k, lse).
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, lse_ref, delta_ref, do_ref, dq_ref,
               acc_ref, *, causal: bool, scale: float, nk: int,
               block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    visible = (qi * block_q + block_q > ki * block_k) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        sblk = q @ kb.T
        if causal:
            sblk = _mask_block(sblk, qi, ki, block_q, block_k)
        p = jnp.exp(sblk - lse_ref[0])  # lse block is (bq, 1)
        dp = do @ vb.T
        ds = p * (dp - delta_ref[0])
        acc_ref[...] += ds @ kb * scale

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, lse_ref, delta_ref, do_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                scale: float, nq: int, block_q: int, block_k: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    visible = (qi * block_q + block_q > ki * block_k) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        sblk = q @ kb.T
        if causal:
            sblk = _mask_block(sblk, qi, ki, block_q, block_k)
        p = jnp.exp(sblk - lse_ref[0])  # lse block is (bq, 1)
        dv_acc[...] += p.T @ do
        dp = do @ vb.T
        ds = p * (dp - delta_ref[0])
        dk_acc[...] += ds.T @ q  # q already carries `scale`

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _bwd_bhsd(q, k, v, lse, do, out, causal, block_q, block_k, interpret):
    bh, s, d = q.shape
    nq, nk = s // block_q, s // block_k
    # Δ_i = do_i · o_i, a cheap row reduction XLA fuses on its own; keeps
    # the trailing unit lane dim the row-stat BlockSpecs need.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    q_spec_i = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    k_spec_j = pl.BlockSpec((1, block_k, d),
                            _kv_index(causal, block_q, block_k))
    row_spec_i = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=d ** -0.5,
                          nk=nk, block_q=block_q, block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[q_spec_i, k_spec_j, k_spec_j, row_spec_i, row_spec_i,
                  q_spec_i],
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, lse, delta, do)

    # dK/dV: grid over K tiles, Q innermost.
    q_idx = _q_index(causal, block_q, block_k)
    q_spec_j = pl.BlockSpec((1, block_q, d), q_idx)
    k_spec_i = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    row_spec_j = pl.BlockSpec((1, block_q, 1), q_idx)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=d ** -0.5,
                          nq=nq, block_q=block_q, block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[q_spec_j, k_spec_i, k_spec_i, row_spec_j, row_spec_j,
                  q_spec_j],
        out_specs=[k_spec_i, k_spec_i],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, lse, delta, do)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp core on (batch*heads, seq, head_dim) arrays
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _fwd_bhsd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd_bhsd(q, k, v, causal, block_q, block_k, interpret)
    # Residuals are O(s·d) + O(s): inputs, output, and the softmax row
    # statistics — never the (s × s) probabilities.
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _bwd_bhsd(q, k, v, lse, do, out, causal, block_q, block_k,
                     interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def _auto_block(s: int, cap: int = 512) -> int:
    """Largest block <= cap that divides s, preferring multiples of the
    128-wide MXU tile (512 measured fastest on v5e; see docs/benchmarks.md)."""
    for cand in range(min(cap, s) - min(cap, s) % 128, 0, -128):
        if s % cand == 0:
            return cand
    best = 1
    for cand in range(2, min(cap, s) + 1):
        if s % cand == 0:
            best = cand
    return best


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    block_q: int | None = None, block_k: int | None = None,
                    interpret: bool | None = None):
    """Exact attention, flash-style, differentiable. Shapes
    (batch, seq, heads, head_dim) — the model zoo's ``attention_fn``
    contract. ``bias`` is not supported by the kernel (use the stock
    attention for biased variants). Block sizes default to the largest
    divisor of ``seq`` <= 512 that is a multiple of 128; explicit block
    sizes must divide ``seq``."""
    if bias is not None:
        raise NotImplementedError(
            "flash_attention does not take a bias; use "
            "models.transformer.dot_product_attention for biased attention")
    b, s, h, d = q.shape
    block_q = _auto_block(s) if block_q is None else min(block_q, s)
    block_k = _auto_block(s) if block_k is None else min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq len {s} must be divisible by block sizes "
            f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhsd(t):
        return jnp.transpose(t, (0, 2, 1, 3)).reshape(b * h, s, d)

    out = _flash(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal,
                 block_q, block_k, interpret)
    return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))


def flash_attention_causal(q, k, v, bias=None, **kw):
    """Causal variant matching the ``attention_fn`` signature."""
    return flash_attention(q, k, v, bias, causal=True, **kw)
