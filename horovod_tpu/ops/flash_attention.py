"""Flash attention as a pallas TPU kernel.

The hot op of transformer training. XLA's stock attention materializes the
(s × s) logits in HBM; this kernel streams K/V blocks through VMEM with an
online softmax so HBM traffic is O(s·d) instead of O(s²) — the standard
flash formulation (Dao et al.), written for the MXU: block sizes default to
128 (the systolic tile), accumulation in f32.

Plugs in anywhere the model zoo accepts an ``attention_fn``
(:class:`horovod_tpu.models.TransformerConfig`) and composes with sequence
parallelism: inside :func:`horovod_tpu.parallel.ulysses_attention` it
kernels the per-head full-sequence attention, and ring attention's
per-block math is the same online-softmax update this kernel runs locally.

Off-TPU (tests, CPU debugging) the kernel runs in pallas interpret mode —
same code path, scalar semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    s = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)

    nk = s // block_k
    if causal:
        # Blocks entirely above the diagonal contribute nothing; bound the
        # loop at the diagonal block.
        ub = (qi * bq + bq + block_k - 1) // block_k
        ub = jnp.minimum(ub, nk)
    else:
        ub = nk

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(ki, carry):
        o, m, l = carry
        kb = k_ref[0, pl.dslice(ki * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.dslice(ki * block_k, block_k), :].astype(jnp.float32)
        sblk = q @ kb.T  # (bq, bk) on the MXU
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            sblk = jnp.where(q_pos >= k_pos, sblk, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1))
        p = jnp.exp(sblk - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1)
        o = o * alpha[:, None] + p @ vb
        return o, m_new, l

    o0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, ub, body, (o0, m0, l0))
    o_ref[0] = (o / l[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_bhsd(q, k, v, causal: bool, block_q: int, block_k: int,
                interpret: bool):
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=d ** -0.5)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v)


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None):
    """Exact attention, flash-style. Shapes (batch, seq, heads, head_dim)
    — the model zoo's ``attention_fn`` contract. ``bias`` is not
    supported by the kernel (use the stock attention for biased variants).
    """
    if bias is not None:
        raise NotImplementedError(
            "flash_attention does not take a bias; use "
            "models.transformer.dot_product_attention for biased attention")
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq len {s} must be divisible by block sizes "
            f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhsd(t):
        return jnp.transpose(t, (0, 2, 1, 3)).reshape(b * h, s, d)

    out = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal,
                      block_q, block_k, interpret)
    return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))


def flash_attention_causal(q, k, v, bias=None, **kw):
    """Causal variant matching the ``attention_fn`` signature."""
    return flash_attention(q, k, v, bias, causal=True, **kw)
