"""Pooled host-buffer allocator — the engines' zero-copy data plane.

The reference keeps one persistent fusion buffer per (device, framework)
and reuses it forever (PersistentBuffer, SURVEY C8; FusionBufferManager,
operations.cc:2035-2074) — buffer reuse, not faster memcpy, is what makes
its small-tensor path cheap (arxiv 1802.05799; arxiv 1810.11112 measures
the copy-in/copy-out phases as the dominant non-network cost). This module
is that seat for the host engines: per-dtype slabs in power-of-two size
classes, checked out for submit snapshots, fusion buffers, wire-staging
and result buffers, and reused across cycles so a steady-state training
loop allocates nothing after warmup (pinned by tests/test_zero_copy.py).

Lifecycle is reference-count driven, not checkin-driven: ``checkout``
returns a numpy VIEW of a pool-owned slab, and a slab becomes reusable
when no view of it remains alive (numpy collapses view chains onto the
owning array, so one ``sys.getrefcount`` probe is exact). That makes
pooling safe by construction — a result view handed to a caller pins its
slab for exactly as long as the caller can observe it, and an executor
returning its input aliased as output can never cause a reuse scribble.

The C++ engine keeps its own twin of this pool inside libhvdcore
(hvdcore.cc BufferPool — explicit Get/Put there, since the C++ loop owns
every buffer lifetime precisely); both feed the same telemetry counters:
``engine.pool.{hits,misses,checkouts}`` and the ``engine.pool.
bytes_resident`` gauge.

Knobs: ``HVD_POOL_MAX_BYTES`` caps the resident slab bytes per pool
(default 1 GiB; ``0`` disables pooling entirely — every checkout is a
plain allocation, the measured "before" of docs/benchmarks.md).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.core import faultline as flt
from horovod_tpu.core import telemetry as tele

DEFAULT_MAX_BYTES = 1 << 30
# Slabs below this round up to it: tiny classes would fragment the pool
# into hundreds of buckets, and CPython routes >=4 KiB allocations to
# malloc, whose blocks are comfortably aligned for every wire dtype.
MIN_CLASS_BYTES = 4096


def max_bytes_from_env() -> int:
    """HVD_POOL_MAX_BYTES (bytes; 0 disables pooling)."""
    v = os.environ.get("HVD_POOL_MAX_BYTES")
    if not v:
        return DEFAULT_MAX_BYTES
    try:
        return max(0, int(v))
    except ValueError:
        return DEFAULT_MAX_BYTES


def class_bytes(nbytes: int) -> int:
    """Size class: next power of two, floored at MIN_CLASS_BYTES.
    Checkouts match their exact class only — a steady-state loop with a
    fixed working set re-hits the same buckets forever, and a 4 KiB
    request can never steal (and force the realloc of) a 256 MiB slab."""
    return max(MIN_CLASS_BYTES, 1 << (max(int(nbytes), 1) - 1).bit_length())


class BufferPool:
    """Per-dtype pooled slabs. Thread-safe; one instance per engine (so
    elastic teardown can poison exactly the dying engine's pool)."""

    def __init__(self, max_bytes: Optional[int] = None,
                 own_gauge: bool = True):
        self.max_bytes = (max_bytes_from_env() if max_bytes is None
                          else int(max_bytes))
        self.enabled = self.max_bytes > 0
        # Whether this pool writes the engine.pool.bytes_resident gauge
        # directly. The native engine's python-side pool does NOT — its
        # stats sync owns the gauge (C++ + python residency combined),
        # and a per-checkout write here would clobber that with the
        # python share alone.
        self._own_gauge = own_gauge
        self._lock = threading.Lock()
        # (dtype, class bytes) -> slabs. Every slab the pool ever retained
        # stays listed; a slab is FREE exactly when only the list holds it.
        self._slabs: Dict[Tuple[np.dtype, int], List[np.ndarray]] = {}
        self._poisoned = False
        self.hits = 0
        self.misses = 0
        self.checkouts = 0
        self.bytes_resident = 0
        # Registry objects cached once: the checkout path must not pay a
        # name lookup per call (both engines feed these same names — the
        # native engine folds its C++ pool's counts in via its stats
        # sync, see native_engine._STAT_COUNTERS).
        self._c_hits = tele.REGISTRY.counter("engine.pool.hits")
        self._c_misses = tele.REGISTRY.counter("engine.pool.misses")
        self._c_checkouts = tele.REGISTRY.counter("engine.pool.checkouts")
        self._g_resident = tele.REGISTRY.gauge("engine.pool.bytes_resident")

    def checkout(self, count: int, dtype) -> np.ndarray:
        """A 1-d array of ``count`` elements, backed by a pooled slab when
        one of the right (dtype, class) is free. The returned view (and
        anything derived from it) pins the slab; dropping every view
        returns the slab to the pool implicitly."""
        return self.checkout_tracked(count, dtype)[0]

    def checkout_tracked(self, count: int, dtype):
        """:meth:`checkout` plus whether the buffer is actually
        pool-tracked (hit, or a retained miss) — the honest value of the
        trace spans' ``pooled`` arg: a cap-exceeded, fault-exhausted or
        poisoned checkout must attribute as plain, not pooled."""
        dtype = np.dtype(dtype)
        count = int(count)
        nbytes = max(count, 1) * dtype.itemsize
        # Fault site engine.pool (core/faultline.py): 'exhausted' forces
        # the cap-reached path — fresh allocation, counted as a miss,
        # nothing retained.
        exhausted = flt.pool_exhausted()
        self.checkouts += 1  # benign data race: monotonic event tally
        self._c_checkouts.inc()
        if not self.enabled or self._poisoned or exhausted:
            self.misses += 1
            self._c_misses.inc()
            return np.empty((count,), dtype), False
        cls = class_bytes(nbytes)
        # The lock covers only the bucket scan/registration: allocation
        # happens outside it — the submit thread and the engine loop
        # share this pool, and a fat critical section would turn every
        # checkout into a GIL/lock handoff between them.
        with self._lock:
            bucket = self._slabs.get((dtype, cls))
            if bucket:
                for slab in bucket:
                    # Free slab: referenced only by the bucket entry, the
                    # loop variable and getrefcount's argument. Any live
                    # view (numpy collapses view chains onto the owning
                    # array) raises the count and skips it.
                    if sys.getrefcount(slab) == 3:
                        self.hits += 1
                        self._c_hits.inc()
                        return slab[:count], True
        self.misses += 1
        self._c_misses.inc()
        with self._lock:
            retain = (not self._poisoned
                      and self.bytes_resident + cls <= self.max_bytes)
        if not retain:
            # Cap reached (or racing a poison): a plain allocation of
            # EXACTLY count elements — class rounding here would double
            # the memory of every over-cap tensor for no reuse benefit.
            return np.empty((count,), dtype), False
        slab = np.empty((cls // dtype.itemsize,), dtype)
        tracked = False
        with self._lock:
            if (not self._poisoned
                    and self.bytes_resident + cls <= self.max_bytes):
                self._slabs.setdefault((dtype, cls), []).append(slab)
                self.bytes_resident += cls
                if self._own_gauge:
                    self._g_resident.set(self.bytes_resident)
                tracked = True
        return slab[:count], tracked

    def snapshot(self, arr):
        """Pool-backed copy of ``arr`` (any layout), shaped like it — the
        submit-time snapshot — plus the tracked flag of
        :meth:`checkout_tracked`. Falls back to a plain copy when
        disabled."""
        a = np.asarray(arr)
        out, tracked = self.checkout_tracked(a.size, a.dtype)
        out = out.reshape(a.shape)
        np.copyto(out, a)
        return out, tracked

    def poison(self):
        """Elastic teardown (Engine.abandon): drop every slab reference so
        nothing checked out by the dying engine can ever be handed to a
        later checkout — a wedged thread parked inside the old backend may
        still be reading its views. Outstanding views keep their slabs
        alive independently; the memory dies with the last view."""
        with self._lock:
            self._poisoned = True
            self._slabs.clear()
            self.bytes_resident = 0
            if self._own_gauge:
                self._g_resident.set(0)

    @property
    def poisoned(self) -> bool:
        return self._poisoned

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "checkouts": self.checkouts,
                    "bytes_resident": self.bytes_resident}


_default: Optional[BufferPool] = None
_default_lock = threading.Lock()


def get_default() -> BufferPool:
    """Process-wide pool for pool users without an engine (a standalone
    JaxExecutor). Engines construct their own instances."""
    global _default
    with _default_lock:
        if _default is None:
            _default = BufferPool()
        return _default
