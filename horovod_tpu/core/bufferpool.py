"""Pooled host-buffer allocator — the engines' zero-copy data plane.

The reference keeps one persistent fusion buffer per (device, framework)
and reuses it forever (PersistentBuffer, SURVEY C8; FusionBufferManager,
operations.cc:2035-2074) — buffer reuse, not faster memcpy, is what makes
its small-tensor path cheap (arxiv 1802.05799; arxiv 1810.11112 measures
the copy-in/copy-out phases as the dominant non-network cost). This module
is that seat for the host engines: per-dtype slabs in power-of-two size
classes, checked out for submit snapshots, fusion buffers, wire-staging
and result buffers, and reused across cycles so a steady-state training
loop allocates nothing after warmup (pinned by tests/test_zero_copy.py).

Lifecycle is reference-count driven, not checkin-driven: ``checkout``
returns a numpy VIEW of a pool-owned slab, and a slab becomes reusable
when no view of it remains alive (numpy collapses view chains onto the
owning array, so one ``sys.getrefcount`` probe is exact). That makes
pooling safe by construction — a result view handed to a caller pins its
slab for exactly as long as the caller can observe it, and an executor
returning its input aliased as output can never cause a reuse scribble.

The C++ engine keeps its own twin of this pool inside libhvdcore
(hvdcore.cc BufferPool — explicit Get/Put there, since the C++ loop owns
every buffer lifetime precisely); both feed the same telemetry counters:
``engine.pool.{hits,misses,checkouts}`` and the ``engine.pool.
bytes_resident`` gauge.

Knobs: ``HVD_POOL_MAX_BYTES`` caps the resident slab bytes per pool
(default 1 GiB; ``0`` disables pooling entirely — every checkout is a
plain allocation, the measured "before" of docs/benchmarks.md).
``HVD_POOL_BIND_MAX`` caps how many tensor NAMES may hold a pre-bound
slab (:meth:`BufferPool.snapshot_bound`; default 1024) — a steady-state
per-step gradient reuses the same slab by name and skips even the
bucket scan. ``HVD_POOL_PROBE_LIMIT`` bounds how many slabs one
checkout may examine for freeness (default 32): probing is O(1), not
O(live views), so a caller draining thousands of small results never
turns the pool scan quadratic.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.core import faultline as flt
from horovod_tpu.core import telemetry as tele

DEFAULT_MAX_BYTES = 1 << 30
# Slabs below this round up to it: tiny classes would fragment the pool
# into hundreds of buckets, and CPython routes >=4 KiB allocations to
# malloc, whose blocks are comfortably aligned for every wire dtype.
MIN_CLASS_BYTES = 4096


def max_bytes_from_env() -> int:
    """HVD_POOL_MAX_BYTES (bytes; 0 disables pooling)."""
    v = os.environ.get("HVD_POOL_MAX_BYTES")
    if not v:
        return DEFAULT_MAX_BYTES
    try:
        return max(0, int(v))
    except ValueError:
        return DEFAULT_MAX_BYTES


def class_bytes(nbytes: int) -> int:
    """Size class: next power of two, floored at MIN_CLASS_BYTES.
    Checkouts match their exact class only — a steady-state loop with a
    fixed working set re-hits the same buckets forever, and a 4 KiB
    request can never steal (and force the realloc of) a 256 MiB slab."""
    return max(MIN_CLASS_BYTES, 1 << (max(int(nbytes), 1) - 1).bit_length())


class BufferPool:
    """Per-dtype pooled slabs. Thread-safe; one instance per engine (so
    elastic teardown can poison exactly the dying engine's pool)."""

    def __init__(self, max_bytes: Optional[int] = None,
                 own_gauge: bool = True):
        self.max_bytes = (max_bytes_from_env() if max_bytes is None
                          else int(max_bytes))
        self.enabled = self.max_bytes > 0
        # Whether this pool writes the engine.pool.bytes_resident gauge
        # directly. The native engine's python-side pool does NOT — its
        # stats sync owns the gauge (C++ + python residency combined),
        # and a per-checkout write here would clobber that with the
        # python share alone.
        self._own_gauge = own_gauge
        self._lock = threading.Lock()
        # (dtype, class bytes) -> slabs. Every slab the pool ever retained
        # stays listed; a slab is FREE exactly when only the list holds it.
        self._slabs: Dict[Tuple[np.dtype, int], List[np.ndarray]] = {}
        # Per-bucket rotating scan cursor. Checkout probes at most
        # _probe_limit slabs starting here: a full scan would be O(live
        # slabs) per checkout, and a 10k-handle synchronize drain piles
        # its still-held result views at the bucket head — the scan then
        # walks every one of them per checkout, O(n^2) per drain
        # (measured: a 10k x 4 KiB drain cost seconds, growing across
        # iterations). Bounded probing keeps checkout O(1); the cursor
        # advance past busy slabs makes freed ones reachable within one
        # bucket revolution.
        self._cursor: Dict[Tuple[np.dtype, int], int] = {}
        self._probe_limit = int(
            os.environ.get("HVD_POOL_PROBE_LIMIT") or 32)
        # name -> slab pre-bound to that tensor name (snapshot_bound).
        # A bound slab lives ONLY here (never in _slabs), so the same
        # getrefcount probe decides freeness: dict + local + argument.
        self._bound: Dict[str, np.ndarray] = {}
        self._bind_max = int(os.environ.get("HVD_POOL_BIND_MAX") or 1024)
        self._poisoned = False
        self.hits = 0
        self.misses = 0
        self.checkouts = 0
        self.bound_hits = 0
        self.bytes_resident = 0
        # Registry objects cached once: the checkout path must not pay a
        # name lookup per call (both engines feed these same names — the
        # native engine folds its C++ pool's counts in via its stats
        # sync, see native_engine._STAT_COUNTERS).
        self._c_hits = tele.REGISTRY.counter("engine.pool.hits")
        self._c_misses = tele.REGISTRY.counter("engine.pool.misses")
        self._c_checkouts = tele.REGISTRY.counter("engine.pool.checkouts")
        self._c_bound_hits = tele.REGISTRY.counter("engine.pool.bound_hits")
        self._g_resident = tele.REGISTRY.gauge("engine.pool.bytes_resident")

    def checkout(self, count: int, dtype) -> np.ndarray:
        """A 1-d array of ``count`` elements, backed by a pooled slab when
        one of the right (dtype, class) is free. The returned view (and
        anything derived from it) pins the slab; dropping every view
        returns the slab to the pool implicitly."""
        return self.checkout_tracked(count, dtype)[0]

    def checkout_tracked(self, count: int, dtype):
        """:meth:`checkout` plus whether the buffer is actually
        pool-tracked (hit, or a retained miss) — the honest value of the
        trace spans' ``pooled`` arg: a cap-exceeded, fault-exhausted or
        poisoned checkout must attribute as plain, not pooled."""
        dtype = np.dtype(dtype)
        count = int(count)
        nbytes = max(count, 1) * dtype.itemsize
        # Fault site engine.pool (core/faultline.py): 'exhausted' forces
        # the cap-reached path — fresh allocation, counted as a miss,
        # nothing retained.
        exhausted = flt.pool_exhausted()
        self.checkouts += 1  # benign data race: monotonic event tally
        self._c_checkouts.inc()
        if not self.enabled or self._poisoned or exhausted:
            self.misses += 1
            self._c_misses.inc()
            return np.empty((count,), dtype), False
        cls = class_bytes(nbytes)
        # The lock covers only the bucket scan/registration: allocation
        # happens outside it — the submit thread and the engine loop
        # share this pool, and a fat critical section would turn every
        # checkout into a GIL/lock handoff between them.
        key = (dtype, cls)
        with self._lock:
            bucket = self._slabs.get(key)
            if bucket:
                # Bounded probe from the rotating cursor (see __init__):
                # at most _probe_limit slabs examined, so checkout stays
                # O(1) even when thousands of views are live in this
                # class. All-busy after the limit falls through to a
                # fresh allocation (an honest miss — everything WAS
                # busy); the cursor lands past the probed busy run so
                # the next checkout resumes where this one gave up.
                k = len(bucket)
                start = self._cursor.get(key, 0) % k
                for j in range(min(k, self._probe_limit)):
                    i = start + j
                    if i >= k:
                        i -= k
                    slab = bucket[i]
                    # Free slab: referenced only by the bucket entry, the
                    # local and getrefcount's argument. Any live view
                    # (numpy collapses view chains onto the owning
                    # array) raises the count and skips it.
                    if sys.getrefcount(slab) == 3:
                        self._cursor[key] = i + 1
                        self.hits += 1
                        self._c_hits.inc()
                        return slab[:count], True
                self._cursor[key] = start + min(k, self._probe_limit)
        self.misses += 1
        self._c_misses.inc()
        with self._lock:
            retain = (not self._poisoned
                      and self.bytes_resident + cls <= self.max_bytes)
        if not retain:
            # Cap reached (or racing a poison): a plain allocation of
            # EXACTLY count elements — class rounding here would double
            # the memory of every over-cap tensor for no reuse benefit.
            return np.empty((count,), dtype), False
        slab = np.empty((cls // dtype.itemsize,), dtype)
        tracked = False
        with self._lock:
            if (not self._poisoned
                    and self.bytes_resident + cls <= self.max_bytes):
                self._slabs.setdefault((dtype, cls), []).append(slab)
                self.bytes_resident += cls
                if self._own_gauge:
                    self._g_resident.set(self.bytes_resident)
                tracked = True
        return slab[:count], tracked

    def snapshot(self, arr):
        """Pool-backed copy of ``arr`` (any layout), shaped like it — the
        submit-time snapshot — plus the tracked flag of
        :meth:`checkout_tracked`. Falls back to a plain copy when
        disabled."""
        a = np.asarray(arr)
        out, tracked = self.checkout_tracked(a.size, a.dtype)
        out = out.reshape(a.shape)
        np.copyto(out, a)
        return out, tracked

    def snapshot_bound(self, name: str, arr):
        """:meth:`snapshot` with name pre-binding: the first submit of a
        stable tensor name dedicates a full-shape slab to that name, and
        every later steady-state submit re-hits it with ONE dict probe —
        no bucket scan, no reshape, no checkout bookkeeping. The slab is
        free again as soon as the engine retires its entry (the engines
        drop their snapshot reference at completion), so a per-step
        gradient reuses one slab forever. Shape or dtype drift retires
        the stale binding and rebinds. The C++ pool's twin is
        GetBound/PutBound in hvdcore.cc."""
        a = np.asarray(arr)
        if not (self.enabled and not self._poisoned):
            return self.snapshot(a)
        with self._lock:
            slab = self._bound.get(name)
            hit = (slab is not None and slab.dtype == a.dtype
                   and slab.shape == a.shape
                   # Free binding: dict entry + local + getrefcount arg.
                   # A live view (the previous submit still in flight)
                   # raises the count and forces the unbound path.
                   and sys.getrefcount(slab) == 3)
            if hit:
                self.checkouts += 1
                self.hits += 1
                self.bound_hits += 1
        if hit:
            self._c_checkouts.inc()
            self._c_hits.inc()
            self._c_bound_hits.inc()
            # Copy outside the lock: only snapshot_bound touches _bound,
            # and a bound slab observed free here cannot be checked out
            # by any other path before this copy lands.
            np.copyto(slab, a)
            return slab, True
        cls = class_bytes(a.nbytes)
        with self._lock:
            stale = self._bound.get(name)
            stale_cls = class_bytes(stale.nbytes) if stale is not None else 0
            ok = ((stale is not None or len(self._bound) < self._bind_max)
                  and self.bytes_resident - stale_cls + cls <= self.max_bytes)
        if not ok:
            # Bind table full or cap reached: plain pooled snapshot.
            return self.snapshot(a)
        # Dedicated full-shape slab (bypasses the pow2 buckets so the
        # refcount probe above stays exact); allocated outside the lock.
        slab = np.empty(a.shape, a.dtype)
        np.copyto(slab, a)
        self.checkouts += 1
        self.misses += 1
        self._c_checkouts.inc()
        self._c_misses.inc()
        with self._lock:
            if self._poisoned:
                return slab, False
            stale = self._bound.pop(name, None)
            if stale is not None:
                self.bytes_resident -= class_bytes(stale.nbytes)
            if (len(self._bound) < self._bind_max
                    and self.bytes_resident + cls <= self.max_bytes):
                self._bound[name] = slab
                self.bytes_resident += cls
                if self._own_gauge:
                    self._g_resident.set(self.bytes_resident)
                return slab, True
        return slab, False

    def poison(self):
        """Elastic teardown (Engine.abandon): drop every slab reference so
        nothing checked out by the dying engine can ever be handed to a
        later checkout — a wedged thread parked inside the old backend may
        still be reading its views. Outstanding views keep their slabs
        alive independently; the memory dies with the last view."""
        with self._lock:
            self._poisoned = True
            self._slabs.clear()
            self._bound.clear()
            self._cursor.clear()
            self.bytes_resident = 0
            if self._own_gauge:
                self._g_resident.set(0)

    @property
    def poisoned(self) -> bool:
        return self._poisoned

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "checkouts": self.checkouts,
                    "bound_hits": self.bound_hits,
                    "bytes_resident": self.bytes_resident}


_default: Optional[BufferPool] = None
_default_lock = threading.Lock()


def get_default() -> BufferPool:
    """Process-wide pool for pool users without an engine (a standalone
    JaxExecutor). Engines construct their own instances."""
    global _default
    with _default_lock:
        if _default is None:
            _default = BufferPool()
        return _default
