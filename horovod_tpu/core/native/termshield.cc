// termshield — park std::terminate instead of dying (elastic worlds only).
//
// Why this exists: when the jax coordination-service HOST dies, every
// surviving client's error-poll RPC fails within ~1 ms and the client
// either LOG(FATAL)s the survivor (the stock callback) or — once
// horovod_tpu replaces that callback to disarm the fatal — throws a
// nanobind cast error that unwinds the agent's poll thread into
// std::terminate (this jaxlib has no Python caster for absl::Status
// callback arguments). Either way the SURVIVOR dies with the host,
// which is the exact opposite of elastic semantics.
//
// The shield converts that terminate into a parked thread: elastic
// worlds already *leak* resources wedged on dead peers (backends,
// dispatch workers — see core/elastic.py) rather than run undefined
// teardown; a parked agent thread is the same doctrine. The process
// stays alive, the KV lease / file-plane failover attributes the real
// casualty, and the world reconfigures.
//
// Installed ONLY under HVD_ELASTIC=1 (core/elastic.bring_up_distributed)
// — a non-elastic run keeps fail-fast std::terminate semantics.

#include <cstdio>
#include <dlfcn.h>
#include <exception>
#include <unistd.h>

extern "C" {

typedef int (*hvd_gil_check_fn)(void);
typedef void *(*hvd_gil_save_fn)(void);

static void hvd_park_terminate() {
  static const char msg[] =
      "[hvd termshield] std::terminate intercepted; parking this thread "
      "(elastic worlds leak wedged threads instead of dying — the "
      "heartbeat lease attributes the real casualty)\n";
  ssize_t ignored = write(2, msg, sizeof(msg) - 1);
  (void)ignored;
  // g++ reaches std::terminate for an unhandled exception WITHOUT
  // unwinding: no destructor ran, so a scoped GIL acquisition in the
  // throwing frame is still held by this thread. Parking while holding
  // it would freeze the whole interpreter — release it first. Symbols
  // resolved dynamically so the shim needs no Python headers and stays
  // harmless in a non-Python process.
  hvd_gil_check_fn gil_check =
      (hvd_gil_check_fn)dlsym(RTLD_DEFAULT, "PyGILState_Check");
  hvd_gil_save_fn gil_save =
      (hvd_gil_save_fn)dlsym(RTLD_DEFAULT, "PyEval_SaveThread");
  if (gil_check && gil_save && gil_check()) gil_save();
  for (;;) pause();  // never return: a returning handler aborts
}

void hvd_termshield_install() { std::set_terminate(hvd_park_terminate); }

}  // extern "C"
