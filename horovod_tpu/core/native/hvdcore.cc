// libhvdcore — native background collective engine for horovod_tpu.
//
// TPU-native re-design of the reference's C++ core (reference:
// horovod/common/operations.cc): one background thread owns the request
// queue and tensor table, drains it every cycle, fuses compatible
// allreduces into flat buffers up to a threshold, executes them through a
// registered executor callback (the XLA data plane lives on the Python
// side), and completes integer handles that framework threads wait on
// (reference: torch/handle_manager.cc).
//
// What is intentionally ABSENT vs the reference: the rank-0 MPI
// negotiation protocol (operations.cc:279-517). A single controller
// process observes its own program order, and SPMD determinism makes
// cross-rank agreement structural rather than negotiated; the duplicate-
// name and shutdown-error semantics are preserved (operations.cc:265-268,
// 1833-1848).
//
// Also here, matching reference subsystems:
//  - stall watchdog (CheckForStalledTensors, operations.cc:1535-1581)
//  - chrome-tracing timeline writer (common/timeline.cc)

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

double SecondsSince(Clock::time_point t0) {
  return std::chrono::duration<double>(Clock::now() - t0).count();
}

// Timed condition-variable wait that stays visible to ThreadSanitizer.
//
// Under a plain build this is cv.wait_for. Under -fsanitize=thread the
// steady-clock wait_for lowers to pthread_cond_clockwait (glibc >= 2.30
// via libstdc++), which this toolchain's TSan does NOT intercept: the
// mutex release/re-acquire inside the wait becomes invisible, every
// happens-before edge through the engine mutex is lost, and TSan
// reports hundreds of false races "between two threads both holding
// mu_". Routing the sanitized build through wait_until(system_clock)
// keeps the wait on the intercepted pthread_cond_timedwait path. The
// system clock can step mid-wait, but engine waits are milliseconds
// and only pace the loop — and this variant exists only inside
// sanitizer builds (HVD_SANITIZE), never in production ones.
#if defined(__SANITIZE_THREAD__)
template <class Pred>
bool WaitFor(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
             double seconds, Pred pred) {
  return cv.wait_until(
      lk,
      std::chrono::system_clock::now() +
          std::chrono::microseconds((long long)(seconds * 1e6)),
      pred);
}
#else
template <class Pred>
bool WaitFor(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
             double seconds, Pred pred) {
  return cv.wait_for(lk, std::chrono::duration<double>(seconds), pred);
}
#endif

// ---------------------------------------------------------------------------
// C ABI shared with Python (ctypes)
// ---------------------------------------------------------------------------

extern "C" {

// HVD_TICK is engine→executor only: end-of-cycle notification carrying the
// cycle's total traffic in `count` (bytes), so the Python-side autotuner
// scores per engine cycle exactly as the reference's ParameterManager does.
enum HvdOp {
  HVD_ALLREDUCE = 0,
  HVD_ALLGATHER = 1,
  HVD_BROADCAST = 2,
  HVD_TICK = 3
};

struct hvd_request {
  int op;
  int dtype_num;   // numpy dtype .num — opaque to C++, round-tripped
  int itemsize;
  int average;
  int root_rank;
  // Engine wire policy code (0 none, 1 int8, 2 fp8 — WIRE_CODES in
  // core/engine.py). Opaque to C++ beyond fusion compatibility and
  // timeline args: the quantization itself happens in the shared data
  // plane behind the executor callback, which is what keeps the two
  // engines' reductions bit-identical under the same policy.
  int wire;
  // Per-tier DCN wire policy code (same code space as `wire`) for the
  // hierarchical two-phase route: the ICI phase reduce-scatters at the
  // resident dtype and ONLY the 1/L cross-tier shard ships quantized.
  // Mutually exclusive with a nonzero `wire` (the Python submit plane
  // enforces it); opaque to C++ beyond fusion compatibility, the
  // negotiation row and timeline args — like `wire`, the actual
  // quantization lives in the shared data plane.
  int wire_dcn;
  double prescale;
  // Seconds until the request's deadline at the moment the executor is
  // called (0 = no deadline; negative = already overdue — the waiter
  // has been failed, the engine is finishing for protocol coherence).
  // A fused batch carries the tightest member deadline. Deadline
  // ENFORCEMENT is the engine's (the loop + watchdog sweep fail the
  // waiter with an attributed CollectiveTimeout); this field only lets
  // the data plane bound its own staging if it cares.
  double deadline_s;
  const char* names;  // ';'-joined tensor names of the fused batch
  void* data;         // fused input buffer
  // Where same-size results must be written. Usually == data (in-place,
  // the historical contract); differs for DONATED single entries, whose
  // caller-owned input buffer the engine may only read.
  void* out;
  long long count;    // elements in data
  // For non-fusable ops the original shape rides along:
  int ndim;
  long long shape[8];
  // Batched-submit plane (hvd_engine_enqueue_n): per-request ownership
  // handoff flag, honored element-by-element inside one batched call
  // exactly like the single-enqueue `donate` argument. Engine->executor
  // requests always carry 0 here (donated inputs reach the data plane
  // through the data/out split instead).
  int donate;
  // Priority class code (PRIORITY_CODES in core/engine.py; lower drains
  // first). The serving-plane scheduling key: the cycle loop sorts ready
  // work by (priority, margin, name), fusion only merges equal-priority
  // entries, and admission budgets are accounted per class. Opaque to
  // the data plane beyond the negotiation row and timeline args.
  int priority;
};

struct hvd_result {
  // Callback contract: for same-size results (allreduce, broadcast) write
  // into req->out (== req->data unless the input was donated) and set
  // data = req->out. For size-changing results (allgather) set data to a
  // buffer from hvd_alloc(); the engine frees it after copying out.
  // Anything else would dangle once the Python callback frame drops its
  // references.
  void* data;
  long long nbytes;
  int ndim;
  long long shape[8];
  // Host->device staging seconds inside the executor (only measured while
  // a timeline is recording): the engine splits it out of the call span as
  // the WAIT_FOR_DATA phase (reference: operations.cc:783-807).
  double stage_s;
  // Bytes the mesh collective actually shipped for this call (int8
  // payload + f32 scales under a quantized wire policy, full width
  // otherwise) and the compressed-policy subset — accumulated into
  // hvd_engine_stats so both engines feed the same
  // engine.wire_bytes{,.compressed} telemetry counters.
  long long wire_bytes;
  long long wire_compressed;
  // Per-tier byte split of the hierarchical two-phase route (zero on
  // every flat route): wire_dcn = quantized 1/L cross-tier payload,
  // wire_ici = full-width intra-tier share. Accumulated into
  // hvd_engine_stats -> engine.wire_bytes.dcn/.ici.
  long long wire_dcn;
  long long wire_ici;
  char error[256];
};

typedef int (*hvd_exec_fn)(void* ctx, hvd_request* req, hvd_result* res);

// Cross-controller negotiation hook (the control plane lives in Python —
// core/coordinator.py — the way the reference's C++ core calls into
// framework-owned services through abstract interfaces, common/common.h).
// `table_json` describes every not-yet-agreed entry in order; the callback
// writes an hvd_alloc()'d decision string to *decision_out (the engine
// frees it):
//   p <cycle_s> <fusion_bytes>      agreed engine params for this round
//   c <0|1>                         round took the response-cache fast path
//                                   (stamped as the NEGOTIATE span's
//                                   `cached` arg)
//   w <seconds>                     one-shot extra wait before next cycle
//   g <i,i,...>                     execute these entries as one group
//   e <i,i,...> <message>           complete these entries with an error
// Unreferenced indices stay pending for the next round. A nonzero return
// poisons negotiation: all pending entries fail with *decision_out as the
// message (e.g. a peer shut down or timed out).
typedef int (*hvd_negotiate_fn)(void* ctx, const char* table_json,
                                char** decision_out);

// Execution-side telemetry snapshot (submit-side counters live in the
// Python binding, which every enqueue passes through anyway). Field
// layout MUST stay in sync with HvdStats in native/__init__.py; the
// Python side computes deltas between reads and folds them into the
// process-wide telemetry registry (core/telemetry.py).
struct hvd_engine_stats {
  long long submitted[3];   // per HvdOp (allreduce/allgather/broadcast)
  long long submitted_bytes;
  long long completed;      // entries completed successfully
  long long errors;         // entries completed with an error
  long long fused_batches;  // fused allreduce executions (batch size > 1)
  long long fused_tensors;  // tensors that rode a fused batch
  long long fused_bytes;    // payload bytes through fusion buffers
  long long cycles;         // loop cycles that executed work
  double cycle_seconds;     // wall time inside those cycles
  long long queue_depth;    // in-flight tensors right now
  long long wire_bytes;     // bytes the mesh collectives shipped
  long long wire_bytes_compressed;  // subset under a quantized policy
  // Per-tier split of the hierarchical two-phase route (zero on flat
  // routes): DCN = quantized 1/L cross-tier payload, ICI = full-width
  // intra-tier share.
  long long wire_bytes_dcn;
  long long wire_bytes_ici;
  // Buffer-pool accounting (entry snapshots, fusion buffers, result
  // buffers — hvdcore's twin of core/bufferpool.py, feeding the same
  // engine.pool.* telemetry through the Python stats sync).
  long long pool_hits;
  long long pool_misses;
  long long pool_checkouts;
  long long pool_bytes_resident;
  // Deadline/cancel plane (engine.deadline_exceeded / engine.cancelled
  // telemetry parity with the python twin's counters).
  long long deadline_exceeded;
  long long cancelled;
  // Batched-submit plane: lock-free submit-ring pressure (full -> locked
  // fallback taken; spins -> CAS retries under producer contention) and
  // name-bound pool slabs reused without a bucket scan. Fed into
  // engine.ring.{full,spins} / engine.pool.bound_hits by the Python
  // stats sync (_STAT_COUNTERS).
  long long ring_full;
  long long ring_spins;
  long long pool_bound_hits;
  // Serving-plane admission control (engine.admission.* counter/gauge
  // parity with the python engine): boundary rejections at submit,
  // deadline-aware sheds, and per-class in-flight entry counts.
  long long admission_rejected;
  long long admission_shed;
  long long admission_inflight_high;
  long long admission_inflight_normal;
  long long admission_inflight_low;
  long long admission_bytes_high;
  long long admission_bytes_normal;
  long long admission_bytes_low;
};

// Latency histogram bucket boundaries in seconds. MUST equal
// LATENCY_BUCKETS_S in core/telemetry.py — hvdcheck rule parity-latency
// diffs the two arrays from source, because world-level rollups merge
// per-rank histograms exactly (same buckets, sum counts) and a skewed
// edge would silently corrupt every fleet quantile.
static const double kLatencyBucketsS[12] = {
    1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0};

// Per-collective latency / phase-residency histograms. Field layout MUST
// stay in sync with HvdLatency in native/__init__.py (hvdcheck rule
// abi-struct). Each instrument is 13 raw bucket counts over
// kLatencyBucketsS (last = +Inf overflow, matching telemetry.Histogram)
// plus an exact value sum; the Python stats sync computes deltas between
// reads and folds them into the registry histograms via
// Histogram.add_counts, which keeps the merged histogram exact. The
// compiled/AOT hot path never feeds these — engine-path completions only.
struct hvd_engine_latency {
  long long allreduce[13];        // engine.latency.allreduce (s)
  long long allgather[13];        // engine.latency.allgather (s)
  long long broadcast[13];        // engine.latency.broadcast (s)
  long long phase_queue[13];      // engine.phase.queue (s)
  long long phase_negotiate[13];  // engine.phase.negotiate (s)
  long long phase_memcpy[13];     // engine.phase.memcpy (s)
  long long phase_exec[13];       // engine.phase.exec (s)
  long long deadline_margin[13];  // engine.deadline.margin (s, clipped >= 0)
  long long class_high[13];       // engine.latency.class.high (s)
  long long class_normal[13];     // engine.latency.class.normal (s)
  long long class_low[13];        // engine.latency.class.low (s)
  double allreduce_sum;
  double allgather_sum;
  double broadcast_sum;
  double phase_queue_sum;
  double phase_negotiate_sum;
  double phase_memcpy_sum;
  double phase_exec_sum;
  double deadline_margin_sum;
  double class_high_sum;
  double class_normal_sum;
  double class_low_sum;
};

void* hvd_alloc(long long nbytes) { return malloc((size_t)nbytes); }

}  // extern "C"

// ---------------------------------------------------------------------------
// Timeline (reference: common/timeline.cc — rank-0 chrome tracing JSON)
// ---------------------------------------------------------------------------

// Tensor names are arbitrary user strings; escape them before interpolating
// into the trace JSON (reference: timeline.cc writes via an escaping JSON
// writer) or a quote/backslash would produce an unparseable file.
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  return out;
}

class Timeline {
 public:
  Timeline() {
    // Clock base + flight-recorder capacity are live even with no file:
    // NowUs() must answer with the real clock (retro-span boundaries)
    // and the ring records the last-N events for post-mortem dumps.
    const char* cap = getenv("HVD_FLIGHT_RECORDER_SIZE");
    ring_cap_ = cap ? atoll(cap) : 512;
    if (ring_cap_ < 16) ring_cap_ = 16;
  }

  void Initialize(const std::string& path) {
    if (path.empty()) return;
    std::lock_guard<std::mutex> g(mu_);
    file_.open(path);
    if (file_.good()) {
      file_ << "[\n";
      active_ = true;
      // start_ stays at construction time: the ring may already hold
      // events, and every clock consumer (NowUs readback, the ring, the
      // file) must share one base.
    }
  }

  bool Active() const { return active_; }

  // Phase span per tensor lane (reference uses one chrome "pid" per tensor
  // name — timeline.cc:60-96). `args` is pre-rendered JSON object body
  // (e.g. dtype/shape — reference: timeline.cc:98-188 WriteEvent args).
  void Begin(const std::string& name, const char* phase,
             const std::string& args = "") {
    Emit(name, phase, 'B', args, -1);
  }
  // End may carry args too (e.g. the `cached` flag on NEGOTIATE_* spans
  // — the attribution is only known when the round resolves).
  void End(const std::string& name, const char* phase,
           const std::string& args = "") {
    Emit(name, phase, 'E', args, -1);
  }

  // Retro-emission at explicit timestamps: a phase boundary learned only
  // after the fact (WAIT_FOR_DATA split out of an executor round-trip).
  void BeginAt(const std::string& name, const char* phase, long long ts_us,
               const std::string& args = "") {
    Emit(name, phase, 'B', args, ts_us);
  }
  void EndAt(const std::string& name, const char* phase, long long ts_us,
             const std::string& args = "") {
    Emit(name, phase, 'E', args, ts_us);
  }

  // Always the real clock, file or no file (a timeline enabled mid-run
  // must never hand callers zero/negative retro timestamps).
  long long NowUs() { return (long long)(SecondsSince(start_) * 1e6); }

  // Zero-duration mark on the tensor's lane (chrome 'i' event) — e.g.
  // RANK_READY instants inside a NEGOTIATE_* span (reference: the
  // per-rank readiness events of timeline.cc:106-130).
  void Instant(const std::string& name, const char* phase,
               const std::string& args = "") {
    Emit(name, phase, 'i', args, -1);
  }

  // Metadata event on pid 0 (HVD_CLOCK and kin): the clock-sync record
  // the merge tool reads. `args` is a pre-rendered JSON object body.
  void Meta(const std::string& name, const std::string& args) {
    std::lock_guard<std::mutex> g(mu_);
    long long ts = (long long)(SecondsSince(start_) * 1e6);
    // Pinned metadata ring: the HVD_CLOCK mapping must never be evicted
    // by span events — every flight dump carries it (newest last).
    meta_ring_.push_back(Rec{ts, 'M', "", name, args});
    if (meta_ring_.size() > 16) meta_ring_.pop_front();
    if (!active_) return;
    Sep();
    file_ << "{\"name\":\"" << JsonEscape(name) << "\",\"ph\":\"M\",\"pid\":0";
    if (!args.empty()) file_ << ",\"args\":{" << args << "}";
    file_ << "}";
    MaybeFlush();
  }

  // Flight recorder export: the ring as a JSON array of
  // {"name": activity, "ph": .., "ts": .., "tensor": .., "args": {..}} —
  // the same event shape the Python twin's Timeline.recent() returns.
  std::string RecentJson() {
    std::lock_guard<std::mutex> g(mu_);
    std::string out = "[";
    bool first = true;
    std::deque<Rec> all(meta_ring_);  // pinned metadata leads the dump
    all.insert(all.end(), ring_.begin(), ring_.end());
    for (auto& r : all) {
      if (!first) out += ",";
      first = false;
      out += "{\"name\":\"";
      out += JsonEscape(r.phase);
      out += "\",\"ph\":\"";
      out += r.ph;
      out += "\",\"ts\":" + std::to_string(r.ts);
      if (!r.tensor.empty())
        out += ",\"tensor\":\"" + JsonEscape(r.tensor) + "\"";
      if (!r.args.empty()) out += ",\"args\":{" + r.args + "}";
      out += "}";
    }
    out += "]";
    return out;
  }

  void Close() {
    std::lock_guard<std::mutex> g(mu_);
    if (!active_) return;
    file_ << "]\n";
    file_.close();
    active_ = false;
  }

 private:
  struct Rec {
    long long ts;
    char ph;
    std::string tensor, phase, args;
  };

  void Record(long long ts, char ph, const std::string& tensor,
              const std::string& phase, const std::string& args) {
    ring_.push_back(Rec{ts, ph, tensor, phase, args});
    if ((long long)ring_.size() > ring_cap_) ring_.pop_front();
  }

  void Sep() {
    if (first_) {
      first_ = false;
    } else {
      file_ << ",\n";
    }
  }

  void MaybeFlush() {
    // 1 s flush horizon like the reference (timeline.h:32).
    if (SecondsSince(last_flush_) > 1.0) {
      file_.flush();
      last_flush_ = Clock::now();
    }
  }

  void Emit(const std::string& name, const char* phase, char ph,
            const std::string& args, long long ts_us) {
    std::lock_guard<std::mutex> g(mu_);
    long long ts =
        ts_us >= 0 ? ts_us : (long long)(SecondsSince(start_) * 1e6);
    // Flight recorder: always on, bounded, never touches disk.
    Record(ts, ph, name, phase, args);
    if (!active_) return;
    int pid;
    auto it = lanes_.find(name);
    if (it == lanes_.end()) {
      pid = (int)lanes_.size() + 1;
      lanes_[name] = pid;
      Sep();
      file_ << "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":" << pid
            << ",\"args\":{\"name\":\"" << JsonEscape(name) << "\"}}";
    } else {
      pid = it->second;
    }
    Sep();
    file_ << "{\"name\":\"" << phase << "\",\"ph\":\"" << ph
          << "\",\"pid\":" << pid << ",\"ts\":" << ts;
    if (ph == 'i') file_ << ",\"s\":\"p\"";  // instant scope: process
    if (!args.empty()) file_ << ",\"args\":{" << args << "}";
    file_ << "}";
    MaybeFlush();
  }

  std::mutex mu_;
  std::ofstream file_;
  std::unordered_map<std::string, int> lanes_;
  Clock::time_point start_ = Clock::now(), last_flush_ = Clock::now();
  std::deque<Rec> ring_, meta_ring_;
  long long ring_cap_ = 512;
  bool active_ = false;
  bool first_ = true;
};

// Engine wire dtype names, by dtype_num code. MUST stay in sync with the
// _DTYPES table in horovod_tpu/core/native_engine.py (the Python side
// assigns the codes; this table only feeds timeline args).
const char* DtypeName(int dtype_num) {
  static const char* kNames[] = {
      "float32",  "float64", "float16", "int8",       "uint8",
      "int16",    "uint16",  "int32",   "uint32",     "int64",
      "uint64",   "bool",    "complex64", "complex128", "bfloat16"};
  if (dtype_num >= 0 && dtype_num < (int)(sizeof(kNames) / sizeof(*kNames)))
    return kNames[dtype_num];
  return "unknown";
}

// Engine wire-policy names by code — MUST stay in sync with WIRE_CODES
// in core/engine.py (nullptr = full width, no arg emitted).
const char* WireName(int wire) {
  switch (wire) {
    case 1: return "int8";
    case 2: return "fp8";
    default: return nullptr;
  }
}

// Collective op names by code (hvd_request.op) — the inspect records'
// `op` field; mirrors _OPS in core/native_engine.py.
const char* OpName(int op) {
  switch (op) {
    case 0: return "allreduce";
    case 1: return "allgather";
    case 2: return "broadcast";
    default: return "unknown";
  }
}

// Priority class names by code (hvd_request.priority) — the inspect
// records' `priority` field; mirrors PRIORITY_NAMES in core/engine.py
// (0 high, 1 normal, 2 low; lower drains first).
const char* PriorityName(int priority) {
  switch (priority) {
    case 0: return "high";
    case 2: return "low";
    default: return "normal";
  }
}

// Clamp an hvd_request.priority code into the class table (the Python
// submit plane validates; this is belt-and-braces for raw C callers so
// admission accounting can never index out of bounds).
int PriorityClass(int priority) {
  return priority < 0 ? 0 : (priority > 2 ? 2 : priority);
}

// Pre-rendered args body for timeline events — dtype + shape (+ the wire
// policy when one applies), the detail the reference writer records
// (timeline.cc:98-188).
//
// FORMATTING CONTRACT (hvdcheck parity-span-args): span-args bodies put
// a space after the colon (`"dtype": ...`), and every other JSON this
// file renders (the chrome event skeleton, the negotiation table) does
// not — that convention is how the analyzer tells span-args keys apart
// from wire-protocol keys when diffing the two engines' vocabularies.
std::string TensorArgs(int dtype_num, const std::vector<long long>& shape,
                       int wire = 0, int wire_dcn = 0, int priority = 1) {
  std::string out = "\"dtype\": \"";
  out += DtypeName(dtype_num);
  out += "\", \"shape\": [";
  for (size_t i = 0; i < shape.size(); ++i) {
    if (i) out += ", ";
    out += std::to_string(shape[i]);
  }
  out += "]";
  if (const char* w = WireName(wire)) {
    out += ", \"wire\": \"";
    out += w;
    out += "\"";
  }
  if (const char* wd = WireName(wire_dcn)) {
    out += ", \"wire_dcn\": \"";
    out += wd;
    out += "\"";
  }
  if (priority != 1) {
    // Serving-plane class attribution (no arg for the default class,
    // like the wire policies above) — same parity-span-args contract.
    out += ", \"priority\": \"";
    out += PriorityName(priority);
    out += "\"";
  }
  return out;
}

// ---------------------------------------------------------------------------
// Buffer pool (the reference's PersistentBuffer seat, SURVEY C8 — C++
// twin of core/bufferpool.py: entry snapshots, fusion buffers and result
// buffers ride reused slabs so steady-state cycles allocate nothing)
// ---------------------------------------------------------------------------

class BufferPool {
 public:
  BufferPool() {
    const char* v = getenv("HVD_POOL_MAX_BYTES");
    max_bytes_ = v ? atoll(v) : (1LL << 30);
    const char* b = getenv("HVD_POOL_BIND_MAX");
    bind_max_ = b ? atoll(b) : 1024;
  }

  // Power-of-two size class, floored at 4 KiB (matches the python pool:
  // exact-class reuse keeps the steady state predictable and a tiny
  // request can never steal a huge slab).
  static size_t ClassOf(long long nbytes) {
    size_t cls = 4096;
    while ((long long)cls < nbytes) cls <<= 1;
    return cls;
  }

  // `tracked` (optional) reports whether the buffer is actually served
  // by the pool (hit, or a miss the pool will retain) — the honest
  // value of the trace spans' "pooled" arg: with pooling disabled or
  // past the resident cap, copies must attribute as plain.
  std::vector<char> Get(long long nbytes, bool* tracked = nullptr) {
    size_t cls = ClassOf(nbytes);
    std::lock_guard<std::mutex> g(mu_);
    checkouts_++;
    if (max_bytes_ > 0) {
      auto it = free_.find(cls);
      if (it != free_.end() && !it->second.empty()) {
        std::vector<char> v = std::move(it->second.back());
        it->second.pop_back();
        hits_++;
        v.resize((size_t)nbytes);
        if (tracked) *tracked = true;
        return v;
      }
    }
    misses_++;
    std::vector<char> v;
    if (max_bytes_ <= 0) {
      // Pooling disabled: a plain allocation of EXACTLY nbytes (class
      // rounding here would make the documented unpooled baseline pay
      // up to 2x host memory per in-flight tensor). Put() ignores it.
      v.resize((size_t)nbytes);
      if (tracked) *tracked = false;
      return v;
    }
    v.reserve(cls);
    v.resize((size_t)nbytes);
    // Account by the same floor-class Put() uses (reserve may
    // over-allocate past `cls`): Get/Put adjustments then cancel
    // exactly and resident_ cannot drift.
    bool retain = resident_ < max_bytes_;
    resident_ += (long long)FloorClass(v.capacity());
    if (tracked) *tracked = retain;
    return v;
  }

  // Largest power-of-two class (>= 4 KiB) a capacity covers.
  static size_t FloorClass(size_t capacity) {
    size_t cls = 4096;
    while ((cls << 1) <= capacity) cls <<= 1;
    return cls;
  }

  // Name-bound checkout (the batched-submit fast path): a steady-state
  // per-step gradient resubmits under a stable name, so its snapshot
  // slab is parked under that name at completion (PutBound) and handed
  // straight back on the next submit — no bucket scan, no resize churn,
  // and the bound reuse is visible as pool_bound_hits (a hit that
  // skipped even the checkout scan). Falls through to the regular Get
  // path on first sight of a name, a size-class change, or past the
  // binding cap (HVD_POOL_BIND_MAX names).
  std::vector<char> GetBound(const std::string& name, long long nbytes,
                             bool* tracked) {
    if (max_bytes_ > 0) {
      size_t cls = ClassOf(nbytes);
      std::unique_lock<std::mutex> lk(mu_);
      auto it = bound_.find(name);
      if (it != bound_.end() && FloorClass(it->second.capacity()) == cls) {
        std::vector<char> v = std::move(it->second);
        bound_.erase(it);
        checkouts_++;
        hits_++;
        bound_hits_++;
        v.resize((size_t)nbytes);
        if (tracked) *tracked = true;
        return v;
      }
      if (it != bound_.end()) {
        // Size class changed: retire the stale binding into the general
        // buckets (same capacity-floored bucket Put() would choose).
        std::vector<char> stale = std::move(it->second);
        bound_.erase(it);
        if (resident_ > max_bytes_) {
          resident_ -= (long long)FloorClass(stale.capacity());
          if (resident_ < 0) resident_ = 0;
        } else {
          free_[FloorClass(stale.capacity())].push_back(std::move(stale));
        }
      }
    }
    return Get(nbytes, tracked);
  }

  // Completion-side twin of GetBound: park the slab under its tensor
  // name instead of the shared buckets. Resident accounting is
  // unchanged (the slab was counted at its original miss; binding only
  // moves where it waits).
  void PutBound(const std::string& name, std::vector<char>&& v) {
    if (v.capacity() < 4096) return;
    std::lock_guard<std::mutex> g(mu_);
    if (max_bytes_ <= 0) return;
    if (resident_ > max_bytes_) {
      resident_ -= (long long)FloorClass(v.capacity());
      if (resident_ < 0) resident_ = 0;
      return;
    }
    auto it = bound_.find(name);
    if (it != bound_.end()) {
      // A same-name binding is already parked (e.g. a rejected duplicate
      // retired its slab first): shunt the incumbent into the shared
      // buckets so its resident accounting survives the re-bind.
      free_[FloorClass(it->second.capacity())].push_back(
          std::move(it->second));
      bound_.erase(it);
    } else if ((long long)bound_.size() >= bind_max_) {
      free_[FloorClass(v.capacity())].push_back(std::move(v));
      return;
    }
    bound_[name] = std::move(v);
  }

  void Put(std::vector<char>&& v) {
    if (v.capacity() < 4096) return;  // sub-class slab: not pool-tracked
    // Bucket by the largest class the capacity COVERS (reserve may
    // over-allocate): every slab in bucket k then has capacity >= k, so
    // a Get hit's resize can never reallocate.
    size_t cls = FloorClass(v.capacity());
    std::lock_guard<std::mutex> g(mu_);
    if (max_bytes_ <= 0) return;  // pooling disabled: nothing tracked
    if (resident_ > max_bytes_) {
      // Over the resident cap: let this slab die.
      resident_ -= (long long)cls;
      if (resident_ < 0) resident_ = 0;
      return;
    }
    free_[cls].push_back(std::move(v));
  }

  bool Enabled() const { return max_bytes_ > 0; }

  // Pre-rendered span-args body for copy spans, from Get()'s `tracked`
  // result: pooled only when the buffer was actually served by the
  // pool, so the pooled-vs-plain trace A/B stays honest under
  // HVD_POOL_MAX_BYTES=0, a blown cap, or the exhausted fault site.
  static const char* PooledArgs(bool tracked) {
    return tracked ? "\"pooled\": true" : "\"pooled\": false";
  }

  void Stats(long long* hits, long long* misses, long long* checkouts,
             long long* resident, long long* bound_hits = nullptr) {
    std::lock_guard<std::mutex> g(mu_);
    *hits = hits_;
    *misses = misses_;
    *checkouts = checkouts_;
    *resident = resident_ > 0 ? resident_ : 0;
    if (bound_hits) *bound_hits = bound_hits_;
  }

 private:
  std::mutex mu_;
  std::map<size_t, std::vector<std::vector<char>>> free_;
  std::unordered_map<std::string, std::vector<char>> bound_;
  long long max_bytes_ = 0;
  long long bind_max_ = 0;
  long long resident_ = 0;  // bytes in pool-tracked slabs (free + lent)
  long long hits_ = 0, misses_ = 0, checkouts_ = 0, bound_hits_ = 0;
};

// ---------------------------------------------------------------------------
// Engine
// ---------------------------------------------------------------------------

struct Entry {
  long long handle;
  std::string name;
  int op;
  int dtype_num;
  int itemsize;
  int average;
  int root_rank;
  int wire;  // engine wire policy code (hvd_request.wire)
  int wire_dcn = 0;  // per-tier DCN policy code (hvd_request.wire_dcn)
  double prescale;
  // Non-donated submits snapshot into a pool-checked-out slab (`data`,
  // returned to the pool at completion); donated submits reference the
  // caller's buffer in place (`ext`, READ-ONLY for the engine — the
  // Python binding keeps the buffer alive until the handle retires).
  std::vector<char> data;
  const char* ext = nullptr;
  long long nbytes = 0;
  std::vector<long long> shape;
  Clock::time_point enqueued;
  // Per-request deadline (absolute; valid when has_deadline). The
  // waiter-failing sweep reads the Pending twin in pending_names_; the
  // Entry copy computes hvd_request.deadline_s at execution.
  Clock::time_point deadline;
  bool has_deadline = false;
  // Batched-submit members: how many requests rode the same
  // hvd_engine_enqueue_n call (stamped on the QUEUE/MEMCPY span args so
  // the trace tools attribute the batch's share per member, not N x),
  // and whether the snapshot slab is name-bound (returned via PutBound
  // instead of the shared buckets at completion).
  int batch_n = 1;
  bool bound = false;
  // Priority class code (hvd_request.priority; lower drains first) —
  // the cycle loop's primary sort key and the fusion compatibility key.
  int priority = 1;

  const char* bytes() const { return ext ? ext : data.data(); }
};

// Per-in-flight-tensor bookkeeping (keyed by name in pending_names_):
// what the stall watchdog and the deadline sweep need to fail a waiter
// with phase attribution while the loop thread may be wedged inside an
// executor call.
struct Pending {
  Clock::time_point enqueued;
  Clock::time_point deadline;
  bool has_deadline = false;
  bool fired = false;   // deadline already failed the waiter
  long long handle = -1;
  const char* phase = "QUEUE";  // -> NEGOTIATE -> ALLREDUCE/...
  // Last phase-transition time: the per-phase residency histograms
  // (engine.phase.*) observe the elapsed span at every transition and
  // once more at completion, mirroring _Entry.phase_since in engine.py.
  Clock::time_point phase_since;
  // Introspection metadata (Engine::Inspect — the hang doctor's
  // per-entry table): stamped from the Entry at both admission sites so
  // the watchdog can export full entry state while the loop thread may
  // be wedged inside an executor call holding the Entry itself.
  int op = 0;
  long long nbytes = 0;
  int dtype_num = 0;
  int wire = 0;
  int batch_n = 1;
  // Priority class code, mirrored from the Entry so admission accounting
  // can decrement the right class at completion and Inspect can name it.
  int priority = 1;
};

// One hvd_engine_enqueue_n call's worth of fully-built entries, published
// into the submit ring as a single pointer (one CAS per batch). The
// handles are pre-allocated so the caller already holds them; the loop
// thread folds them into the engine tables at the next drain.
struct SubmitBatch;

struct HandleState {
  bool done = false;
  std::string error;
  // Pool-checked-out result buffer; the destructor (last reference —
  // after CopyResult/Drop retired the handle and every waiter left
  // WaitMeta) returns it to the pool, which the shared_ptr keeps alive.
  std::vector<char> result;
  std::vector<long long> shape;
  std::shared_ptr<BufferPool> pool;

  ~HandleState() {
    if (pool) pool->Put(std::move(result));
  }
};

struct SubmitBatch {
  std::vector<Entry> entries;
  std::vector<std::shared_ptr<HandleState>> handles;
};

// Lock-free bounded MPSC submit ring (Vyukov bounded-queue shape with a
// single consumer): producers CAS-claim a slot and publish a SubmitBatch
// pointer via the slot's sequence number; the consumer side is "whoever
// holds the engine mutex" (the loop each cycle, or any reader API that
// folds before looking at engine state), which serializes Pop without a
// second lock. The submit fast path therefore never takes mu_ — on a
// full ring the caller falls back to the locked path.
class SubmitRing {
 public:
  SubmitRing() {
    const char* v = getenv("HVD_SUBMIT_RING_SIZE");
    long long want = v ? atoll(v) : 256;
    size_ = 2;
    while (size_ < want && size_ < (1 << 16)) size_ <<= 1;
    slots_.reset(new Slot[size_]);
    for (long long i = 0; i < size_; ++i)
      slots_[i].seq.store((uint64_t)i, std::memory_order_relaxed);
  }

  // Multi-producer publish; false when the ring is full (the caller
  // takes the locked fallback). `spins` counts CAS retries lost to
  // producer contention (engine.ring.spins).
  bool Push(SubmitBatch* b, long long* spins) {
    uint64_t pos = head_.load(std::memory_order_relaxed);
    for (;;) {
      Slot& s = slots_[pos & (uint64_t)(size_ - 1)];
      uint64_t seq = s.seq.load(std::memory_order_acquire);
      long long dif = (long long)seq - (long long)pos;
      if (dif == 0) {
        if (head_.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed)) {
          s.batch = b;
          s.seq.store(pos + 1, std::memory_order_release);
          count_.fetch_add(1, std::memory_order_release);
          return true;
        }
        (*spins)++;  // CAS lost to another producer; pos was reloaded
      } else if (dif < 0) {
        return false;  // full: a lap behind the consumer
      } else {
        pos = head_.load(std::memory_order_relaxed);
      }
    }
  }

  // Single-consumer pop — caller MUST hold the engine mutex. Returns
  // nullptr when empty (or when the next slot is claimed but not yet
  // published; the count stays armed and the caller retries next wake).
  SubmitBatch* Pop() {
    Slot& s = slots_[tail_ & (uint64_t)(size_ - 1)];
    uint64_t seq = s.seq.load(std::memory_order_acquire);
    if ((long long)seq - (long long)(tail_ + 1) < 0) return nullptr;
    SubmitBatch* b = s.batch;
    s.seq.store(tail_ + (uint64_t)size_, std::memory_order_release);
    tail_++;
    count_.fetch_sub(1, std::memory_order_release);
    return b;
  }

  // Cheap wait-predicate probe: batches published (or mid-publish).
  bool Armed() const { return count_.load(std::memory_order_acquire) > 0; }

 private:
  struct Slot {
    std::atomic<uint64_t> seq{0};
    SubmitBatch* batch = nullptr;
  };
  std::unique_ptr<Slot[]> slots_;
  long long size_ = 0;
  std::atomic<uint64_t> head_{0};
  std::atomic<long long> count_{0};
  uint64_t tail_ = 0;  // consumer cursor, guarded by the engine mutex
};

class Engine {
 public:
  Engine(double cycle_s, long long fusion_bytes, double stall_s,
         const char* timeline_path)
      : cycle_s_(cycle_s), fusion_bytes_(fusion_bytes), stall_s_(stall_s),
        pool_(std::make_shared<BufferPool>()) {
    if (timeline_path && timeline_path[0]) timeline_.Initialize(timeline_path);
    loop_ = std::thread(&Engine::Loop, this);
    watchdog_ = std::thread(&Engine::Watchdog, this);
  }

  ~Engine() {
    Shutdown();
    if (loop_.joinable()) loop_.join();
    if (watchdog_.joinable()) watchdog_.join();
    timeline_.Close();
  }

  void SetExecutor(hvd_exec_fn fn, void* ctx) {
    std::lock_guard<std::mutex> g(mu_);
    exec_fn_ = fn;
    exec_ctx_ = ctx;
  }

  void SetNegotiator(hvd_negotiate_fn fn, void* ctx) {
    std::lock_guard<std::mutex> g(mu_);
    neg_fn_ = fn;
    neg_ctx_ = ctx;
  }

  // Divert cycles through the negotiator (multi-controller worlds). The
  // Python side flips this on once topology knows several controller
  // processes exist and a coordination service is reachable.
  void SetNegotiationActive(int on) {
    std::lock_guard<std::mutex> g(mu_);
    neg_active_ = on != 0;
    cv_.notify_all();  // idle loop must start ticking rounds immediately
  }

  // Live-tunable engine parameters (the autotuner drives these; reference:
  // ParameterManager::SetAutoTuning + readback, parameter_manager.cc).
  void SetParams(double cycle_s, long long fusion_bytes) {
    std::lock_guard<std::mutex> g(mu_);
    if (cycle_s > 0) cycle_s_ = cycle_s;
    if (fusion_bytes >= 0) fusion_bytes_ = fusion_bytes;
  }

  // Readback for the Python mirror (negotiated rounds update the C++
  // values directly via the decision's 'p' line).
  void GetParams(double* cycle_s, long long* fusion_bytes) {
    std::lock_guard<std::mutex> g(mu_);
    if (cycle_s) *cycle_s = cycle_s_;
    if (fusion_bytes) *fusion_bytes = fusion_bytes_;
  }

  // Fallback ordering when negotiation is disabled: sort each drained
  // cycle by tensor name so thread-racy enqueue order within a cycle
  // cannot diverge across controller processes. Per-cycle only — this
  // mode additionally requires a single enqueue thread with identical
  // program order on every process; the negotiated path does not.
  void SetSortByName(int on) {
    std::lock_guard<std::mutex> g(mu_);
    sort_by_name_ = on != 0;
  }

  // Admission budgets per priority class (index = class code; 0 =
  // unlimited; a null array leaves that budget family unchanged).
  // Atomics, not mu_: the batched submit fast path reads them without
  // the engine lock.
  void SetAdmission(const long long* max_inflight,
                    const long long* max_bytes) {
    for (int i = 0; i < 3; ++i) {
      if (max_inflight)
        adm_max_inflight_[i].store(max_inflight[i],
                                   std::memory_order_relaxed);
      if (max_bytes)
        adm_max_bytes_[i].store(max_bytes[i], std::memory_order_relaxed);
    }
  }

  long long Enqueue(int op, const char* name, int dtype_num, int itemsize,
                    const void* data, const long long* shape, int ndim,
                    int average, int root_rank, double prescale, int wire,
                    int wire_dcn, int donate, int priority, double deadline_s,
                    char* err) {
    std::unique_lock<std::mutex> lk(mu_);
    FoldRingLocked();  // duplicate check must see ring-published names
    if (shutdown_) {
      snprintf(err, 256, "Horovod engine has been shut down");
      return -1;
    }
    std::string sname(name);
    if (pending_names_.count(sname)) {  // NOLINT — map keyed by name
      // Reference: duplicate in-flight names rejected
      // (operations.cc:265-268, 2293-2296).
      snprintf(err, 256,
               "a collective named '%s' is already pending; names must be "
               "unique among in-flight tensors", name);
      return -1;
    }
    // Admission control (serving plane; twin of _check_admission_locked
    // in engine.py): a class at budget is rejected SYNCHRONOUSLY at the
    // submit boundary — never mid-flight, never tearing a fused batch —
    // and a deadline'd submit whose remaining margin is provably under
    // the observed p50 queue+negotiate residency is shed up front
    // instead of rotting in QUEUE. The lowercase 'admission'/'shed'
    // markers are the binding's contract for mapping these errors onto
    // AdmissionRejected.
    int cls = PriorityClass(priority);
    {
      long long count = 1;
      for (int i = 0; i < ndim; ++i) count *= shape[i];
      long long nbytes = count * itemsize;
      long long limit = adm_max_inflight_[cls].load(std::memory_order_relaxed);
      long long blimit = adm_max_bytes_[cls].load(std::memory_order_relaxed);
      if (limit > 0 &&
          adm_inflight_[cls].load(std::memory_order_relaxed) + 1 > limit) {
        admission_rejected_.fetch_add(1, std::memory_order_relaxed);
        snprintf(err, 256,
                 "admission rejected for '%s': priority class '%s' is at "
                 "its in-flight budget (%lld requests, "
                 "HVD_ADMISSION_MAX_INFLIGHT); resubmit after in-flight "
                 "work completes, or raise the budget",
                 name, PriorityName(cls), limit);
        return -1;
      }
      if (blimit > 0 &&
          adm_bytes_[cls].load(std::memory_order_relaxed) + nbytes > blimit) {
        admission_rejected_.fetch_add(1, std::memory_order_relaxed);
        snprintf(err, 256,
                 "admission rejected for '%s': priority class '%s' is at "
                 "its bytes budget (%lld bytes, HVD_ADMISSION_MAX_BYTES); "
                 "resubmit after in-flight work completes, or raise the "
                 "budget", name, PriorityName(cls), blimit);
        return -1;
      }
      if (deadline_s > 0) {
        double est = QueueLatencyEstimateLocked();
        if (est >= 0 && deadline_s < est) {
          admission_shed_.fetch_add(1, std::memory_order_relaxed);
          snprintf(err, 256,
                   "shed '%s': its remaining deadline is smaller than the "
                   "current p50 queue+negotiate latency (%.1f ms) — it "
                   "would expire in QUEUE (deadline-aware fast-fail; "
                   "counted in engine.admission.shed)",
                   name, est * 1e3);
          return -1;
        }
      }
    }
    Entry e;
    e.handle = next_handle_++;
    e.name = std::move(sname);
    e.op = op;
    e.dtype_num = dtype_num;
    e.itemsize = itemsize;
    e.average = average;
    e.root_rank = root_rank;
    e.wire = wire;
    e.wire_dcn = wire_dcn;
    e.prescale = prescale;
    e.priority = cls;
    long long count = 1;
    for (int i = 0; i < ndim; ++i) count *= shape[i];
    e.nbytes = count * itemsize;
    // Submit-time snapshot as a MEMCPY span at the head of QUEUE; the
    // END args carry the zero-copy attribution (pooled slab copy vs
    // donated ownership handoff that skipped the copy entirely).
    long long t0 = timeline_.NowUs();
    const char* mem_args;
    if (donate) {
      // Ownership handoff: reference the caller's buffer in place (the
      // Python binding pins it until the handle retires); the engine
      // only READS it — results land in pool buffers.
      e.ext = (const char*)data;
      mem_args = "\"donated\": true";
    } else {
      bool tracked = false;
      e.data = pool_->Get(e.nbytes, &tracked);
      memcpy(e.data.data(), data, (size_t)e.nbytes);
      mem_args = BufferPool::PooledArgs(tracked);
    }
    e.shape.assign(shape, shape + ndim);
    e.enqueued = Clock::now();
    Pending p;
    p.enqueued = e.enqueued;
    p.phase_since = e.enqueued;
    p.handle = e.handle;
    p.op = e.op;
    p.nbytes = e.nbytes;
    p.dtype_num = e.dtype_num;
    p.wire = e.wire;
    p.batch_n = e.batch_n;
    p.priority = e.priority;
    if (deadline_s > 0) {
      e.has_deadline = true;
      e.deadline = e.enqueued + std::chrono::duration_cast<Clock::duration>(
          std::chrono::duration<double>(deadline_s));
      p.has_deadline = true;
      p.deadline = e.deadline;
      deadline_count_++;
      // Break the watchdog's (possibly stall_s_/5-long) idle sleep NOW:
      // its tightened sweep tick alone would only take effect on the
      // next wait, far past this request's deadline.
      deadline_kick_ = true;
    }
    pending_names_[e.name] = p;
    if (op >= 0 && op < 3) stats_.submitted[op]++;
    stats_.submitted_bytes += e.nbytes;
    // Admission accounting: incremented once per admitted entry,
    // decremented once at Stage (every completion path).
    adm_inflight_[cls].fetch_add(1, std::memory_order_relaxed);
    adm_bytes_[cls].fetch_add(e.nbytes, std::memory_order_relaxed);
    auto hs = std::make_shared<HandleState>();
    hs->pool = pool_;
    handles_[e.handle] = std::move(hs);
    long long h = e.handle;
    // Args ride the END only (the python twin's shape — the trace CLI
    // reads zero-copy attribution off span ends, like NEGOTIATE's
    // `cached`).
    timeline_.BeginAt(e.name, "QUEUE", t0);  // ring records w/o file too
    timeline_.BeginAt(e.name, "MEMCPY", t0);
    timeline_.EndAt(e.name, "MEMCPY", timeline_.NowUs(), mem_args);
    queue_.push_back(std::move(e));
    lk.unlock();
    cv_.notify_all();
    return h;
  }

  // Batched submit (hvd_engine_enqueue_n): one call, one snapshot pass,
  // one ring publish, one wakeup for N requests. The fast path takes NO
  // engine lock — handles come off the atomic counter, snapshots go
  // through the pool's own (uncontended) lock, and the fully-built
  // batch is CAS-published into the submit ring for the loop (or the
  // next locked reader) to fold. Whole-batch rejections (mixed ops,
  // intra-batch duplicate names) happen synchronously; a duplicate
  // against an already-IN-FLIGHT name is only decidable at fold time
  // and fails that request's handle instead — the waiter sees the same
  // duplicate-name error at synchronize, which both engines document
  // as the batched-submit contract.
  int EnqueueN(hvd_request* reqs, int n, long long* handles_out, char* err) {
    if (n <= 0) {
      snprintf(err, 256, "batched submit needs at least one request");
      return -1;
    }
    if (shutdown_flag_.load(std::memory_order_seq_cst)) {
      snprintf(err, 256, "Horovod engine has been shut down");
      return -1;
    }
    for (int i = 0; i < n; ++i) {
      if (reqs[i].op < 0 || reqs[i].op > 2) {
        snprintf(err, 256, "batched submit: unsupported op code %d",
                 reqs[i].op);
        return -1;
      }
      if (reqs[i].op != reqs[0].op) {
        snprintf(err, 256,
                 "a batched submit must be a single collective op; this "
                 "batch mixes op %d with op %d", reqs[0].op, reqs[i].op);
        return -1;
      }
    }
    {
      std::unordered_set<std::string> seen;
      for (int i = 0; i < n; ++i) {
        if (!seen.insert(reqs[i].names).second) {
          snprintf(err, 256,
                   "a collective named '%s' appears twice in one batched "
                   "submit; names must be unique among in-flight tensors",
                   reqs[i].names);
          return -1;
        }
      }
    }
    // Whole-batch admission pre-check, all-or-nothing BEFORE any
    // snapshot or handle is allocated: admission never tears a fused
    // batch (the cancel doctrine), so a batch that would blow any class
    // budget is rejected whole, synchronously. Check-then-add is two
    // steps without mu_ (this is the lock-free fast path) — concurrent
    // producers can overshoot a budget by one batch; budgets are
    // backpressure, not hard caps. The in-flight reservation is
    // released at Stage, or at AdmitEntryLocked's fail path for entries
    // that never reach it.
    {
      long long need_n[3] = {0, 0, 0};
      long long need_b[3] = {0, 0, 0};
      for (int i = 0; i < n; ++i) {
        int cls = PriorityClass(reqs[i].priority);
        long long count = 1;
        for (int d = 0; d < reqs[i].ndim; ++d) count *= reqs[i].shape[d];
        need_n[cls]++;
        need_b[cls] += count * reqs[i].itemsize;
      }
      for (int cls = 0; cls < 3; ++cls) {
        if (!need_n[cls]) continue;
        long long limit =
            adm_max_inflight_[cls].load(std::memory_order_relaxed);
        long long blimit =
            adm_max_bytes_[cls].load(std::memory_order_relaxed);
        if (limit > 0 &&
            adm_inflight_[cls].load(std::memory_order_relaxed) +
                    need_n[cls] > limit) {
          admission_rejected_.fetch_add(1, std::memory_order_relaxed);
          snprintf(err, 256,
                   "admission rejected for this batched submit: %lld "
                   "requests in priority class '%s' would exceed its "
                   "in-flight budget (%lld requests, "
                   "HVD_ADMISSION_MAX_INFLIGHT) — the batch is rejected "
                   "whole; admission never tears a fused batch",
                   need_n[cls], PriorityName(cls), limit);
          return -1;
        }
        if (blimit > 0 &&
            adm_bytes_[cls].load(std::memory_order_relaxed) +
                    need_b[cls] > blimit) {
          admission_rejected_.fetch_add(1, std::memory_order_relaxed);
          snprintf(err, 256,
                   "admission rejected for this batched submit: %lld "
                   "bytes in priority class '%s' would exceed its bytes "
                   "budget (%lld bytes, HVD_ADMISSION_MAX_BYTES) — the "
                   "batch is rejected whole; admission never tears a "
                   "fused batch",
                   need_b[cls], PriorityName(cls), blimit);
          return -1;
        }
      }
      for (int cls = 0; cls < 3; ++cls) {
        if (!need_n[cls]) continue;
        adm_inflight_[cls].fetch_add(need_n[cls], std::memory_order_relaxed);
        adm_bytes_[cls].fetch_add(need_b[cls], std::memory_order_relaxed);
      }
    }
    auto* b = new SubmitBatch;
    b->entries.reserve(n);
    b->handles.reserve(n);
    long long base = next_handle_.fetch_add(n);
    long long t0 = timeline_.NowUs();
    for (int i = 0; i < n; ++i) {
      hvd_request& r = reqs[i];
      Entry e;
      e.handle = base + i;
      e.name = r.names;  // single name per batched request, not ';'-joined
      e.op = r.op;
      e.dtype_num = r.dtype_num;
      e.itemsize = r.itemsize;
      e.average = r.average;
      e.root_rank = r.root_rank;
      e.wire = r.wire;
      e.wire_dcn = r.wire_dcn;
      e.prescale = r.prescale;
      e.priority = PriorityClass(r.priority);
      long long count = 1;
      for (int d = 0; d < r.ndim; ++d) count *= r.shape[d];
      e.nbytes = count * r.itemsize;
      e.batch_n = n;
      std::string mem_args;
      if (r.donate) {
        e.ext = (const char*)r.data;
        mem_args = "\"donated\": true";
      } else {
        bool tracked = false;
        e.data = pool_->GetBound(e.name, e.nbytes, &tracked);
        memcpy(e.data.data(), r.data, (size_t)e.nbytes);
        e.bound = true;
        mem_args = BufferPool::PooledArgs(tracked);
      }
      mem_args += ", \"batch_n\": ";
      mem_args += std::to_string(n);
      e.shape.assign(r.shape, r.shape + r.ndim);
      e.enqueued = Clock::now();
      if (r.deadline_s > 0) {
        e.has_deadline = true;
        e.deadline =
            e.enqueued + std::chrono::duration_cast<Clock::duration>(
                             std::chrono::duration<double>(r.deadline_s));
      }
      auto hs = std::make_shared<HandleState>();
      hs->pool = pool_;
      timeline_.BeginAt(e.name, "QUEUE", t0);
      timeline_.BeginAt(e.name, "MEMCPY", t0);
      timeline_.EndAt(e.name, "MEMCPY", timeline_.NowUs(), mem_args);
      handles_out[i] = e.handle;
      b->handles.push_back(std::move(hs));
      b->entries.push_back(std::move(e));
    }
    long long spins = 0;
    if (!ring_.Push(b, &spins)) {
      // Ring full: locked fallback. Fold FIRST so this batch cannot
      // overtake batches already published in the ring.
      ring_full_.fetch_add(1, std::memory_order_relaxed);
      std::lock_guard<std::mutex> g(mu_);
      FoldRingLocked();
      for (size_t i = 0; i < b->entries.size(); ++i)
        AdmitEntryLocked(b->entries[i], b->handles[i]);
      delete b;
    }
    if (spins) ring_spins_.fetch_add(spins, std::memory_order_relaxed);
    cv_.notify_all();
    if (shutdown_flag_.load(std::memory_order_seq_cst)) {
      // Shutdown raced the publish: the loop's final drain may already
      // be done — rescue the batch ourselves (admitting under shutdown_
      // fails every waiter with the shutdown error). seq_cst ordering
      // guarantees this recheck or Join's post-join fold sees the batch.
      std::lock_guard<std::mutex> g(mu_);
      FoldRingLocked();
    }
    return 0;
  }

  // -1 unknown, 0 pending, 1 done ok, 2 done with an error. The ok/err
  // split lets the binding release donated-buffer pins only on clean
  // completions (an errored one may be a deadline expiry whose entry —
  // and in-place buffer reference — is still in flight).
  int Poll(long long handle) {
    std::lock_guard<std::mutex> g(mu_);
    FoldRingLocked();  // a ring-published handle registers at fold time
    auto it = handles_.find(handle);
    if (it == handles_.end()) return -1;
    if (!it->second->done) return 0;
    return it->second->error.empty() ? 1 : 2;
  }

  // Cooperative cancel: 0 = marked (the loop retires a pre-announce
  // entry locally; an announced/executing one completes cross-rank and
  // discards its result), -1 = unknown handle or already complete.
  int Cancel(long long handle) {
    {
      std::lock_guard<std::mutex> g(mu_);
      FoldRingLocked();
      auto it = handles_.find(handle);
      if (it == handles_.end() || it->second->done) return -1;
      bool in_flight = false;
      for (auto& kv : pending_names_)
        if (kv.second.handle == handle) { in_flight = true; break; }
      if (!in_flight) return -1;
      cancelled_.insert(handle);
    }
    cv_.notify_all();  // retire promptly even on an idle engine
    return 0;
  }

  // Blocks until completion. 0 ok, 1 collective error, -1 unknown handle.
  int WaitMeta(long long handle, long long* nbytes, int* ndim,
               long long* shape8, char* err) {
    std::shared_ptr<HandleState> hs;
    {
      std::unique_lock<std::mutex> lk(mu_);
      FoldRingLocked();  // also rescues a ring batch after a bare Shutdown
      auto it = handles_.find(handle);
      if (it == handles_.end()) return -1;
      hs = it->second;
      cv_done_.wait(lk, [&] { return hs->done; });
    }
    if (!hs->error.empty()) {
      snprintf(err, 256, "%s", hs->error.c_str());
      return 1;
    }
    *nbytes = (long long)hs->result.size();
    *ndim = (int)hs->shape.size();
    for (size_t i = 0; i < hs->shape.size() && i < 8; ++i)
      shape8[i] = hs->shape[i];
    return 0;
  }

  // Copies result out and retires the handle. 0 ok, -1 unknown/short.
  int CopyResult(long long handle, void* out, long long cap) {
    std::shared_ptr<HandleState> hs;
    {
      std::lock_guard<std::mutex> g(mu_);
      FoldRingLocked();
      auto it = handles_.find(handle);
      if (it == handles_.end()) return -1;
      hs = it->second;
      handles_.erase(it);
    }
    if (!hs->done || (long long)hs->result.size() > cap) return -1;
    memcpy(out, hs->result.data(), hs->result.size());
    return 0;
  }

  // Retires an errored/unneeded handle.
  void Drop(long long handle) {
    std::lock_guard<std::mutex> g(mu_);
    FoldRingLocked();  // an unfolded handle would re-register after erase
    handles_.erase(handle);
  }

  long long PendingCount() {
    std::lock_guard<std::mutex> g(mu_);
    FoldRingLocked();
    return (long long)pending_names_.size();
  }

  // ';'-joined names of the in-flight tensors (the quiesce report's
  // drained/still-pending attribution — the python twin reports NAMES,
  // so the binding must too). Returns the pending count; the joined
  // string is truncated at cap (never mid-name: a name that does not
  // fit is dropped whole).
  long long PendingNames(char* out, long long cap) {
    std::lock_guard<std::mutex> g(mu_);
    FoldRingLocked();
    long long used = 0;
    if (cap > 0) out[0] = '\0';
    for (auto& kv : pending_names_) {
      long long need = (long long)kv.first.size() + (used > 0 ? 1 : 0);
      if (used + need + 1 > cap) break;
      if (used > 0) out[used++] = ';';
      memcpy(out + used, kv.first.c_str(), kv.first.size());
      used += (long long)kv.first.size();
      out[used] = '\0';
    }
    return (long long)pending_names_.size();
  }

  // Per-entry introspection (hvd_engine_inspect — the hang doctor's raw
  // table): one JSON object per newline-separated line for every
  // in-flight tensor, full state rather than PendingNames' bare name
  // list. Record keys and their order MUST mirror ENGINE_INSPECT_KEYS in
  // core/engine.py — hvdcheck rule parity-doctor machine-diffs the two.
  // Same truncation protocol as PendingNames, at record granularity: a
  // record that does not fit is dropped whole and the TRUE count is
  // returned, so callers grow the buffer until the parsed line count
  // matches. (Wire-protocol JSON: no space after the colon — see the
  // TensorArgs formatting contract above.)
  long long Inspect(char* out, long long cap) {
    std::lock_guard<std::mutex> g(mu_);
    FoldRingLocked();
    long long used = 0;
    if (cap > 0) out[0] = '\0';
    Clock::time_point now = Clock::now();
    for (auto& kv : pending_names_) {
      const Pending& p = kv.second;
      long long phase_age_us = (long long)(
          std::chrono::duration<double>(now - p.phase_since).count() * 1e6);
      std::string rec = "{\"name\":\"" + JsonEscape(kv.first) + "\"";
      rec += ",\"op\":\"";
      rec += OpName(p.op);
      rec += "\",\"phase\":\"";
      rec += p.phase;
      rec += "\",\"phase_age_us\":" + std::to_string(phase_age_us);
      rec += ",\"bytes\":" + std::to_string(p.nbytes);
      rec += ",\"dtype\":\"";
      rec += DtypeName(p.dtype_num);
      rec += "\",\"wire\":\"";
      const char* w = WireName(p.wire);
      rec += w ? w : "none";
      rec += "\",\"batch_n\":" + std::to_string(p.batch_n);
      rec += ",\"priority\":\"";
      rec += PriorityName(p.priority);
      rec += "\"";
      if (p.has_deadline) {
        long long rem_us = (long long)(
            std::chrono::duration<double>(p.deadline - now).count() * 1e6);
        rec += ",\"deadline_remaining_us\":" + std::to_string(rem_us);
      } else {
        rec += ",\"deadline_remaining_us\":null";
      }
      rec += ",\"round\":" + std::to_string(neg_round_) + "}";
      long long need = (long long)rec.size() + (used > 0 ? 1 : 0);
      if (used + need + 1 > cap) break;
      if (used > 0) out[used++] = '\n';
      memcpy(out + used, rec.c_str(), rec.size());
      used += (long long)rec.size();
      out[used] = '\0';
    }
    return (long long)pending_names_.size();
  }

  void GetStats(hvd_engine_stats* out) {
    {
      std::lock_guard<std::mutex> g(mu_);
      FoldRingLocked();
      *out = stats_;
      out->queue_depth = (long long)pending_names_.size();
    }
    out->ring_full = ring_full_.load(std::memory_order_relaxed);
    out->ring_spins = ring_spins_.load(std::memory_order_relaxed);
    out->admission_rejected =
        admission_rejected_.load(std::memory_order_relaxed);
    out->admission_shed = admission_shed_.load(std::memory_order_relaxed);
    out->admission_inflight_high =
        adm_inflight_[0].load(std::memory_order_relaxed);
    out->admission_inflight_normal =
        adm_inflight_[1].load(std::memory_order_relaxed);
    out->admission_inflight_low =
        adm_inflight_[2].load(std::memory_order_relaxed);
    out->admission_bytes_high = adm_bytes_[0].load(std::memory_order_relaxed);
    out->admission_bytes_normal =
        adm_bytes_[1].load(std::memory_order_relaxed);
    out->admission_bytes_low = adm_bytes_[2].load(std::memory_order_relaxed);
    pool_->Stats(&out->pool_hits, &out->pool_misses, &out->pool_checkouts,
                 &out->pool_bytes_resident, &out->pool_bound_hits);
  }

  // --- latency / phase-residency histograms (latency_, guarded by mu_) ---

  // Bucket index for a value: same <= rule as telemetry.Histogram.observe
  // (first bound the value does not exceed; 12 = the +Inf overflow).
  static int LatencyBucket(double v) {
    for (int i = 0; i < 12; ++i)
      if (v <= kLatencyBucketsS[i]) return i;
    return 12;
  }

  static void ObserveInto(long long* counts, double* sum, double v) {
    counts[LatencyBucket(v)]++;
    *sum += v;
  }

  // Residency class of a phase-attribution string — dispatch on the first
  // letter (QUEUE / NEGOTIATE_* / everything else = executing) rather
  // than spelling new ALL-CAPS literals the parity-spans vocabulary diff
  // would flag. Mirrors _phase_class in engine.py.
  void ObservePhaseLocked(const char* phase, double v) {
    if (phase != nullptr && phase[0] == 'Q')
      ObserveInto(latency_.phase_queue, &latency_.phase_queue_sum, v);
    else if (phase != nullptr && phase[0] == 'N')
      ObserveInto(latency_.phase_negotiate, &latency_.phase_negotiate_sum, v);
    else
      ObserveInto(latency_.phase_exec, &latency_.phase_exec_sum, v);
  }

  // One observation per fusion-buffer copy pass that performs a real
  // copy (pack, and the staging copy-out — the python twin unpacks by
  // view and observes no copy-out; values may differ across engines,
  // only names and buckets are parity-checked).
  void ObserveMemcpy(double v) {
    std::lock_guard<std::mutex> g(mu_);
    ObserveInto(latency_.phase_memcpy, &latency_.phase_memcpy_sum, v);
  }

  // End-to-end submit->complete latency per op class AND per priority
  // class (the serving-plane engine.latency.class.* split), mirroring
  // record_complete_latency in engine.py.
  void ObserveCompleteLocked(int op, double latency_s, int priority) {
    if (op == HVD_ALLGATHER)
      ObserveInto(latency_.allgather, &latency_.allgather_sum, latency_s);
    else if (op == HVD_BROADCAST)
      ObserveInto(latency_.broadcast, &latency_.broadcast_sum, latency_s);
    else
      ObserveInto(latency_.allreduce, &latency_.allreduce_sum, latency_s);
    int cls = PriorityClass(priority);
    if (cls == 0)
      ObserveInto(latency_.class_high, &latency_.class_high_sum, latency_s);
    else if (cls == 2)
      ObserveInto(latency_.class_low, &latency_.class_low_sum, latency_s);
    else
      ObserveInto(latency_.class_normal, &latency_.class_normal_sum,
                  latency_s);
  }

  // p50(queue) + p50(negotiate) from the phase-residency histograms —
  // the shed gate's latency floor. Negative until the queue histogram
  // holds 8+ samples (SHED_MIN_SAMPLES in engine.py: a cold engine
  // never sheds); negotiate joins only once it has samples of its own.
  // The estimate is the median bucket's upper edge — coarser than the
  // python twin's log interpolation; only the shed counter vocabulary
  // is parity-checked, not the estimate. Caller holds mu_.
  double QueueLatencyEstimateLocked() {
    double q = BucketP50(latency_.phase_queue);
    if (q < 0) return -1.0;
    double neg = BucketP50(latency_.phase_negotiate);
    return neg < 0 ? q : q + neg;
  }

  static double BucketP50(const long long* counts) {
    long long total = 0;
    for (int i = 0; i < 13; ++i) total += counts[i];
    if (total < 8) return -1.0;
    long long half = (total + 1) / 2, cum = 0;
    for (int i = 0; i < 12; ++i) {
      cum += counts[i];
      if (cum >= half) return kLatencyBucketsS[i];
    }
    return kLatencyBucketsS[11];  // median in the +Inf overflow bucket
  }

  void GetLatency(hvd_engine_latency* out) {
    std::lock_guard<std::mutex> g(mu_);
    *out = latency_;
  }

  void Shutdown() {
    {
      std::lock_guard<std::mutex> g(mu_);
      if (shutdown_) return;
      shutdown_ = true;
    }
    // seq_cst pairs with EnqueueN's post-publish recheck: a producer
    // that misses this store has already published, and either its own
    // recheck or the next locked fold (loop drain, Join, any reader)
    // fails the batch with the shutdown error.
    shutdown_flag_.store(true, std::memory_order_seq_cst);
    cv_.notify_all();
  }

  // Join worker threads after Shutdown. Separate from destruction so the
  // Python side can quiesce the engine and then LEAK it: destroying a
  // condition_variable while a synchronize() caller is still inside
  // WaitMeta would be UB, and the binding cannot prove no such caller
  // exists.
  void Join() {
    Shutdown();
    if (loop_.joinable()) loop_.join();
    if (watchdog_.joinable()) watchdog_.join();
    {
      // A producer that published before seeing shutdown_flag_ may have
      // left a batch in the ring after the loop's final drain; fail its
      // waiters now (admitting under shutdown_ completes them inline).
      std::lock_guard<std::mutex> g(mu_);
      FoldRingLocked();
    }
    timeline_.Close();  // workers joined: no further Emit is possible
  }

  // External instant mark (the python negotiator trampoline emits
  // RANK_READY marks here — the negotiation tables live python-side).
  void TimelineInstant(const char* name, const char* phase,
                       const char* args) {
    timeline_.Instant(name, phase, args ? args : "");
  }

  void TimelineMeta(const char* name, const char* args) {
    timeline_.Meta(name ? name : "", args ? args : "");
  }

  long long TimelineNow() { return timeline_.NowUs(); }

  // Flight-recorder export: writes the ring as a NUL-terminated JSON
  // array into `out`. Returns bytes written, or the required size
  // (> cap) when the buffer is too small — the caller retries bigger.
  long long RecentEvents(char* out, long long cap) {
    std::string s = timeline_.RecentJson();
    if ((long long)s.size() + 1 > cap) {
      if (cap > 0) out[0] = 0;
      return (long long)s.size() + 1;
    }
    memcpy(out, s.c_str(), s.size() + 1);
    return (long long)s.size();
  }

 private:
  // Fold one fast-path entry into the engine tables — caller holds mu_.
  // Duplicate-vs-in-flight and shutdown are only decidable here; both
  // complete the handle inline (Stage()/Complete() re-acquire mu_ and
  // must not be called with it held).
  void AdmitEntryLocked(Entry& e, const std::shared_ptr<HandleState>& hs) {
    handles_[e.handle] = hs;
    char msg[512];
    const char* fail = nullptr;
    if (shutdown_) {
      fail = "Horovod engine has been shut down";
    } else if (pending_names_.count(e.name)) {
      snprintf(msg, sizeof(msg),
               "a collective named '%s' is already pending; names must be "
               "unique among in-flight tensors", e.name.c_str());
      fail = msg;
    }
    if (fail) {
      stats_.errors++;
      hs->error = fail;
      hs->done = true;
      // This entry never reaches Stage: release its EnqueueN-time
      // admission reservation here.
      adm_inflight_[PriorityClass(e.priority)].fetch_sub(
          1, std::memory_order_relaxed);
      adm_bytes_[PriorityClass(e.priority)].fetch_sub(
          e.nbytes, std::memory_order_relaxed);
      std::string qargs;
      if (e.batch_n > 1)
        qargs = "\"batch_n\": " + std::to_string(e.batch_n);
      timeline_.End(e.name, "QUEUE", qargs);
      if (!e.ext && e.data.capacity()) {
        if (e.bound)
          pool_->PutBound(e.name, std::move(e.data));
        else
          pool_->Put(std::move(e.data));
      }
      cv_done_.notify_all();
      return;
    }
    Pending p;
    p.enqueued = e.enqueued;
    p.phase_since = e.enqueued;
    p.handle = e.handle;
    p.op = e.op;
    p.nbytes = e.nbytes;
    p.dtype_num = e.dtype_num;
    p.wire = e.wire;
    p.batch_n = e.batch_n;
    p.priority = e.priority;
    if (e.has_deadline) {
      p.has_deadline = true;
      p.deadline = e.deadline;
      deadline_count_++;
      deadline_kick_ = true;
      // The publish-side notify predates the fold, so the watchdog may
      // already be back in a coarse sleep; kick it again now that the
      // deadline is visible.
      cv_.notify_all();
    }
    pending_names_[e.name] = p;
    if (e.op >= 0 && e.op < 3) stats_.submitted[e.op]++;
    stats_.submitted_bytes += e.nbytes;
    queue_.push_back(std::move(e));
  }

  // Drain the submit ring into the engine tables — caller holds mu_.
  // Every mu_-taking entry point folds first, so fast-path submits are
  // visible to any reader or cycle that observes engine state.
  void FoldRingLocked() {
    while (SubmitBatch* b = ring_.Pop()) {
      for (size_t i = 0; i < b->entries.size(); ++i)
        AdmitEntryLocked(b->entries[i], b->handles[i]);
      delete b;
    }
  }

  void Loop() {
    while (true) {
      std::deque<Entry> batch;
      bool negotiate;
      {
        std::unique_lock<std::mutex> lk(mu_);
        double cycle = cycle_s_ + extra_wait_;
        extra_wait_ = 0.0;
        // One wait serves both modes. Negotiated mode must tick rounds
        // even with nothing local to submit — peers block on our round
        // message (reference: every rank gathers a possibly-empty request
        // list each tick, operations.cc:2117) — and its idle pacing comes
        // from the control plane's 'w' backoff folded into `cycle` above,
        // not from a different wait here. A fresh enqueue, a ring
        // publish, or shutdown cuts either mode's sleep short.
        WaitFor(cv_, lk, cycle,
                [&] { return shutdown_ || !queue_.empty() || ring_.Armed(); });
        // On shutdown, leave queued entries for the failure drain below:
        // executing them could call into Python during teardown.
        if (shutdown_) break;
        FoldRingLocked();
        batch.swap(queue_);
        negotiate = neg_active_ && neg_fn_ != nullptr;
      }
      // Deadline sweep rides the cycle (reference rhythm: RunLoopOnce
      // housekeeping). The watchdog thread sweeps too, for the case
      // where THIS thread is about to wedge inside an executor call.
      SweepDeadlines();
      if (negotiate) {
        NegotiateCycle(batch);
      } else {
        RunCycle(batch);
      }
    }
    // Fail whatever remains (reference: SHUT_DOWN_ERROR path,
    // operations.cc:1833-1848).
    std::deque<Entry> rest;
    {
      std::lock_guard<std::mutex> g(mu_);
      FoldRingLocked();  // under shutdown_ this fails ring batches inline
      rest.swap(queue_);
    }
    for (auto& e : rest)
      Complete(e, nullptr, 0, nullptr, "Horovod engine has been shut down");
    for (auto& e : negotiating_)
      Complete(e, nullptr, 0, nullptr, "Horovod engine has been shut down");
    negotiating_.clear();
  }

  static const char* NegPhase(int op) {
    switch (op) {
      case HVD_ALLGATHER: return "NEGOTIATE_ALLGATHER";
      case HVD_BROADCAST: return "NEGOTIATE_BROADCAST";
      default: return "NEGOTIATE_ALLREDUCE";
    }
  }

  void FailAllNegotiating(const std::string& msg) {
    for (auto& e : negotiating_) {
      timeline_.End(e.name, NegPhase(e.op));
      Complete(e, nullptr, 0, nullptr, msg.c_str());
    }
    negotiating_.clear();
  }

  // One negotiation round: describe every not-yet-agreed entry to the
  // control plane, execute exactly the groups it returns (the reference's
  // coordinated half of RunLoopOnce, operations.cc:1921-2172).
  void NegotiateCycle(std::deque<Entry>& fresh) {
    Clock::time_point t0 = Clock::now();
    for (auto& e : fresh) {
      // Cancel/deadline cull BEFORE the announce: a pre-announce entry
      // retires locally (no peer lists it); once announced it must
      // complete cross-rank and discard (a round cannot be torn).
      if (CullEntry(e)) continue;
      SetPhase(e.name, NegPhase(e.op));
      timeline_.Begin(e.name, NegPhase(e.op));
      negotiating_.push_back(std::move(e));
    }
    if (neg_poisoned_) {
      if (!negotiating_.empty()) FailAllNegotiating(neg_poison_);
      return;
    }
    // Serialize the table (names JSON-escaped; everything else numeric).
    std::string table = "[";
    for (size_t i = 0; i < negotiating_.size(); ++i) {
      Entry& e = negotiating_[i];
      if (i) table += ",";
      table += "{\"n\":\"" + JsonEscape(e.name) + "\"";
      table += ",\"o\":" + std::to_string(e.op);
      table += ",\"d\":" + std::to_string(e.dtype_num);
      table += ",\"i\":" + std::to_string(e.itemsize);
      table += ",\"s\":[";
      for (size_t j = 0; j < e.shape.size(); ++j) {
        if (j) table += ",";
        table += std::to_string(e.shape[j]);
      }
      table += "],\"a\":" + std::to_string(e.average);
      table += ",\"r\":" + std::to_string(e.root_rank);
      // %.17g round-trips the double exactly; std::to_string's fixed 6
      // decimals would collapse small prescales to 0 and fingerprint
      // differently from the python twin's full-precision JSON floats
      // (a spurious "Mismatched reduction options" across mixed engines).
      char pbuf[32];
      snprintf(pbuf, sizeof(pbuf), "%.17g", e.prescale);
      table += ",\"p\":";
      table += pbuf;
      table += ",\"t\":" + std::to_string(SecondsSince(e.enqueued));
      table += ",\"b\":" + std::to_string(e.nbytes);
      table += ",\"w\":" + std::to_string(e.wire);
      table += ",\"wd\":" + std::to_string(e.wire_dcn);
      table += ",\"y\":" + std::to_string(e.priority) + "}";
    }
    table += "]";
    hvd_negotiate_fn fn;
    void* ctx;
    {
      std::lock_guard<std::mutex> g(mu_);
      fn = neg_fn_;
      ctx = neg_ctx_;
      // Round counter for the inspect records: peers whose tables
      // disagree show diverging rounds in the doctor's cross-rank diff.
      neg_round_++;
    }
    char* decision = nullptr;
    int rc = fn(ctx, table.c_str(), &decision);
    if (rc != 0) {
      neg_poisoned_ = true;
      neg_poison_ = decision ? decision : "negotiation failed";
      free(decision);
      FailAllNegotiating(neg_poison_);
      return;
    }
    size_t before = negotiating_.size();
    long long executed_bytes = ParseAndExecute(decision ? decision : "");
    free(decision);
    if (negotiating_.size() < before) {
      // Entries completed this round ('g' or 'e' groups) — the same
      // executed-work rule the Python twin counts cycles by.
      std::lock_guard<std::mutex> g(mu_);
      stats_.cycles++;
      stats_.cycle_seconds += SecondsSince(t0);
    }
    if (executed_bytes > 0) {
      hvd_request req{};
      req.op = HVD_TICK;
      req.names = "";
      req.count = executed_bytes;
      hvd_result res{};
      CallExecutor(&req, &res);  // autotune traffic report; best-effort
    }
  }

  // Decision grammar: see hvd_negotiate_fn. Returns executed bytes.
  long long ParseAndExecute(const std::string& decision) {
    std::vector<bool> done(negotiating_.size(), false);
    long long executed_bytes = 0;
    bool cached = false;  // response-cache fast round ('c 1' line)
    size_t pos = 0;
    while (pos < decision.size()) {
      size_t eol = decision.find('\n', pos);
      if (eol == std::string::npos) eol = decision.size();
      std::string line = decision.substr(pos, eol - pos);
      pos = eol + 1;
      if (line.empty()) continue;
      char kind = line[0];
      std::string rest = line.size() > 2 ? line.substr(2) : "";
      if (kind == 'p') {
        double cyc = 0;
        long long fus = -1;
        if (sscanf(rest.c_str(), "%lf %lld", &cyc, &fus) == 2)
          SetParams(cyc, fus);
        continue;
      }
      if (kind == 'c') {
        cached = atoi(rest.c_str()) != 0;
        continue;
      }
      if (kind == 'w') {
        double w = atof(rest.c_str());
        std::lock_guard<std::mutex> g(mu_);
        if (w > 0) extra_wait_ = w;
        continue;
      }
      if (kind != 'g' && kind != 'e') continue;
      // indices up to first space; for 'e' the remainder is the message
      size_t sp = rest.find(' ');
      std::string idxs = sp == std::string::npos ? rest : rest.substr(0, sp);
      std::string msg = sp == std::string::npos ? "" : rest.substr(sp + 1);
      std::vector<Entry*> group;
      size_t ip = 0;
      bool bad = false;
      while (ip < idxs.size()) {
        size_t comma = idxs.find(',', ip);
        if (comma == std::string::npos) comma = idxs.size();
        long long idx = atoll(idxs.substr(ip, comma - ip).c_str());
        ip = comma + 1;
        if (idx < 0 || idx >= (long long)negotiating_.size() || done[idx]) {
          bad = true;
          break;
        }
        done[idx] = true;
        group.push_back(&negotiating_[idx]);
      }
      if (bad || group.empty()) continue;  // malformed line: leave pending
      for (auto* e : group)
        timeline_.End(e->name, NegPhase(e->op),
                      cached ? "\"cached\": true" : "\"cached\": false");
      if (kind == 'e') {
        for (auto* e : group)
          Complete(*e, nullptr, 0, nullptr,
                   msg.empty() ? "mismatched collective" : msg.c_str());
        continue;
      }
      for (auto* e : group) executed_bytes += e->nbytes;
      if (group[0]->op == HVD_ALLREDUCE) {
        ExecAllreduceBatch(group);
      } else {
        for (auto* e : group) ExecSingle(*e);
      }
    }
    // Compact: drop completed entries, preserve order of the rest.
    std::vector<Entry> remaining;
    remaining.reserve(negotiating_.size());
    for (size_t i = 0; i < negotiating_.size(); ++i)
      if (!done[i]) remaining.push_back(std::move(negotiating_[i]));
    negotiating_.swap(remaining);
    return executed_bytes;
  }

  // Fuse allreduces per (dtype, average, prescale) in request order up to
  // the threshold (reference: operations.cc:2035-2074); other ops run
  // singly, in order.
  void RunCycle(std::deque<Entry>& entries) {
    Clock::time_point t0 = Clock::now();
    long long fusion_limit;
    bool sort_by_name;
    {
      std::lock_guard<std::mutex> g(mu_);
      fusion_limit = fusion_bytes_;
      sort_by_name = sort_by_name_;
    }
    if (entries.size() > 1) {
      // Serving-plane drain order (twin of _run_cycle in engine.py):
      // priority class first, always. Deadline margin breaks ties ONLY
      // in single-controller mode — the margin clock is process-local,
      // so the multi-controller no-KV fallback must keep the
      // cross-rank-deterministic (priority, name) key. Name last for
      // determinism either way.
      Clock::time_point now = Clock::now();
      bool by_name = sort_by_name;
      std::stable_sort(
          entries.begin(), entries.end(),
          [now, by_name](const Entry& a, const Entry& b) {
            if (a.priority != b.priority) return a.priority < b.priority;
            if (!by_name) {
              double ma =
                  a.has_deadline
                      ? std::chrono::duration<double>(a.deadline - now)
                            .count()
                      : std::numeric_limits<double>::infinity();
              double mb =
                  b.has_deadline
                      ? std::chrono::duration<double>(b.deadline - now)
                            .count()
                      : std::numeric_limits<double>::infinity();
              if (ma != mb) return ma < mb;
            }
            return a.name < b.name;
          });
    }
    std::vector<Entry*> fuse;
    long long fuse_bytes = 0;
    long long cycle_bytes = 0;
    auto flush = [&] {
      if (!fuse.empty()) ExecAllreduceBatch(fuse);
      fuse.clear();
      fuse_bytes = 0;
    };
    for (auto& e : entries) {
      if (CullEntry(e)) continue;  // cancelled/overdue: retire locally
      cycle_bytes += e.nbytes;
      if (e.op == HVD_ALLREDUCE) {
        bool compatible =
            fuse.empty() ||
            (fuse[0]->priority == e.priority &&
             fuse[0]->dtype_num == e.dtype_num &&
             fuse[0]->average == e.average &&
             fuse[0]->prescale == e.prescale &&
             fuse[0]->wire == e.wire &&
             fuse[0]->wire_dcn == e.wire_dcn &&
             fuse_bytes + e.nbytes <= fusion_limit);
        if (!compatible) flush();
        fuse.push_back(&e);
        fuse_bytes += e.nbytes;
      } else {
        flush();
        ExecSingle(e);
      }
    }
    flush();
    if (!entries.empty()) {
      {
        std::lock_guard<std::mutex> g(mu_);
        stats_.cycles++;
        stats_.cycle_seconds += SecondsSince(t0);
      }
      hvd_request req{};
      req.op = HVD_TICK;
      req.names = "";
      req.count = cycle_bytes;
      hvd_result res{};
      CallExecutor(&req, &res);  // best-effort; ignored on error
    }
  }

  int CallExecutor(hvd_request* req, hvd_result* res) {
    hvd_exec_fn fn;
    void* ctx;
    {
      std::lock_guard<std::mutex> g(mu_);
      fn = exec_fn_;
      ctx = exec_ctx_;
    }
    if (!fn) {
      snprintf(res->error, sizeof(res->error), "no executor registered");
      return 1;
    }
    return fn(ctx, req, res);
  }

  void ExecAllreduceBatch(std::vector<Entry*>& batch) {
    // Assemble fused buffer + names.
    std::string names;
    long long total = 0;
    int itemsize = batch[0]->itemsize;
    for (auto* e : batch) {
      if (!names.empty()) names += ';';
      names += e->name;
      total += e->nbytes / itemsize;
    }
    if (batch.size() > 1) {
      std::lock_guard<std::mutex> g(mu_);
      stats_.fused_batches++;
      stats_.fused_tensors += (long long)batch.size();
      stats_.fused_bytes += total * itemsize;
    }
    // Fusion buffer from the pool, reused across cycles (the reference's
    // persistent fusion buffer, operations.cc:2035-2074). A batch of ONE
    // skips the copy entirely: the entry's own buffer is the request
    // buffer (with a pooled bounce output when the input was donated —
    // donated buffers are read-only to the engine).
    std::vector<char> fused, bounce;
    hvd_request req{};
    if (batch.size() > 1) {
      bool tracked = false;
      fused = pool_->Get(total * itemsize, &tracked);
      long long off = 0;
      Clock::time_point t_pack = Clock::now();
      for (auto* e : batch) {
        timeline_.Begin(e->name, "MEMCPY_IN_FUSION_BUFFER");
        memcpy(fused.data() + off, e->bytes(), (size_t)e->nbytes);
        off += e->nbytes;
        timeline_.End(e->name, "MEMCPY_IN_FUSION_BUFFER",
                      BufferPool::PooledArgs(tracked));
      }
      // One engine.phase.memcpy observation per pack pass (the python
      // twin times its fusion pack the same way).
      ObserveMemcpy(SecondsSince(t_pack));
      req.data = fused.data();
      req.out = fused.data();
    } else if (batch[0]->ext) {
      bounce = pool_->Get(batch[0]->nbytes);
      req.data = (void*)batch[0]->ext;
      req.out = bounce.data();
    } else {
      req.data = batch[0]->data.data();
      req.out = batch[0]->data.data();
    }
    req.op = HVD_ALLREDUCE;
    req.dtype_num = batch[0]->dtype_num;
    req.itemsize = itemsize;
    req.average = batch[0]->average;
    req.wire = batch[0]->wire;  // batch is policy-uniform (fusion key)
    req.wire_dcn = batch[0]->wire_dcn;
    req.priority = batch[0]->priority;  // priority-uniform too
    req.prescale = batch[0]->prescale;
    req.deadline_s = BatchDeadlineRemaining(batch);
    req.names = names.c_str();
    req.count = total;
    req.ndim = 1;
    req.shape[0] = total;
    for (auto* e : batch) SetPhase(e->name, "ALLREDUCE");
    hvd_result res{};
    long long t0 = timeline_.NowUs();
    int rc = CallExecutor(&req, &res);
    {
      // Wire-byte accounting (engine.wire_bytes{,.compressed} parity
      // with the python twin's record_wire): the executor measured what
      // the mesh collective actually shipped.
      std::lock_guard<std::mutex> g(mu_);
      stats_.wire_bytes += res.wire_bytes;
      stats_.wire_bytes_compressed += res.wire_compressed;
      stats_.wire_bytes_dcn += res.wire_dcn;
      stats_.wire_bytes_ici += res.wire_ici;
    }
    {
      // WAIT_FOR_DATA = the host->device staging slice the executor
      // measured; the rest of the round-trip is the collective proper
      // (reference: operations.cc:783-807 then the MPI/NCCL op).
      long long t1 = timeline_.NowUs();
      long long split = t0 + (long long)(res.stage_s * 1e6);
      if (split > t1) split = t1;
      for (auto* e : batch) {
        timeline_.BeginAt(e->name, "WAIT_FOR_DATA", t0);
        timeline_.EndAt(e->name, "WAIT_FOR_DATA", split);
        timeline_.BeginAt(e->name, "ALLREDUCE", split,
                          TensorArgs(e->dtype_num, e->shape, e->wire,
                                     e->wire_dcn, e->priority));
        timeline_.EndAt(e->name, "ALLREDUCE", t1);
      }
    }
    // Stage every result (copies out of the fused buffer), retire the
    // cycle's pool buffers, THEN wake the waiters — see Stage/Notify.
    std::vector<std::shared_ptr<HandleState>> staged;
    staged.reserve(batch.size());
    if (rc != 0) {
      for (auto* e : batch)
        staged.push_back(Stage(*e, nullptr, 0, nullptr, res.error));
    } else if (res.nbytes != total * itemsize) {
      for (auto* e : batch)
        staged.push_back(Stage(*e, nullptr, 0, nullptr,
                               "executor returned wrong allreduce size"));
    } else {
      long long roff = 0;
      for (auto* e : batch) {
        staged.push_back(Stage(
            *e, (char*)res.data + roff, e->nbytes, &e->shape, nullptr,
            batch.size() > 1 ? "MEMCPY_OUT_FUSION_BUFFER" : nullptr));
        roff += e->nbytes;
      }
      if (res.data && res.data != req.data && res.data != req.out)
        free(res.data);
    }
    RetireBuffers(fused, bounce);
    for (auto& hs : staged) Notify(hs);
  }

  // Return cycle-scoped pool buffers (fusion / donated-input bounce)
  // after every Complete copied out of them.
  void RetireBuffers(std::vector<char>& fused, std::vector<char>& bounce) {
    if (fused.capacity()) pool_->Put(std::move(fused));
    if (bounce.capacity()) pool_->Put(std::move(bounce));
  }

  void ExecSingle(Entry& e) {
    hvd_request req{};
    req.op = e.op;
    req.dtype_num = e.dtype_num;
    req.itemsize = e.itemsize;
    req.average = e.average;
    req.root_rank = e.root_rank;
    req.wire = e.wire;
    req.wire_dcn = e.wire_dcn;
    req.priority = e.priority;
    req.prescale = e.prescale;
    req.names = e.name.c_str();
    std::vector<char> bounce;
    req.data = (void*)e.bytes();
    if (e.ext && e.op != HVD_ALLGATHER) {
      // Donated input is read-only to the engine: same-size results
      // (broadcast) land in a pooled bounce buffer instead. Allgather
      // results always come back in the callback's own hvd_alloc()
      // buffer — no bounce needed.
      bounce = pool_->Get(e.nbytes);
      req.out = bounce.data();
    } else {
      req.out = req.data;
    }
    req.deadline_s = DeadlineRemaining(e);
    req.count = e.nbytes / e.itemsize;
    req.ndim = (int)e.shape.size();
    for (size_t i = 0; i < e.shape.size() && i < 8; ++i)
      req.shape[i] = e.shape[i];
    const char* phase = e.op == HVD_ALLGATHER ? "ALLGATHER" : "BROADCAST";
    SetPhase(e.name, phase);
    hvd_result res{};
    long long t0 = timeline_.NowUs();
    int rc = CallExecutor(&req, &res);
    {
      std::lock_guard<std::mutex> g(mu_);
      stats_.wire_bytes += res.wire_bytes;
      stats_.wire_bytes_compressed += res.wire_compressed;
      stats_.wire_bytes_dcn += res.wire_dcn;
      stats_.wire_bytes_ici += res.wire_ici;
    }
    {
      long long t1 = timeline_.NowUs();
      long long split = t0 + (long long)(res.stage_s * 1e6);
      if (split > t1) split = t1;
      timeline_.BeginAt(e.name, "WAIT_FOR_DATA", t0);
      timeline_.EndAt(e.name, "WAIT_FOR_DATA", split);
      timeline_.BeginAt(e.name, phase, split,
                        TensorArgs(e.dtype_num, e.shape, 0, 0, e.priority));
      timeline_.EndAt(e.name, phase, t1);
    }
    std::shared_ptr<HandleState> hs;
    if (rc != 0) {
      hs = Stage(e, nullptr, 0, nullptr, res.error);
    } else {
      std::vector<long long> shape(res.shape, res.shape + res.ndim);
      hs = Stage(e, (char*)res.data, res.nbytes, &shape, nullptr);
      if (res.data && res.data != req.data && res.data != req.out)
        free(res.data);
    }
    if (bounce.capacity()) pool_->Put(std::move(bounce));
    Notify(hs);
  }

  // `copy_phase` (e.g. MEMCPY_OUT_FUSION_BUFFER) wraps just the result
  // copy-out so the span nests inside the still-open QUEUE span
  // (reference: out-copy spans, operations.cc:1359-1374).
  //
  // Completion is split in two so every cycle-scoped pool buffer can
  // retire BEFORE any waiter wakes: Stage() lands the result/error in
  // the handle and returns the entry's snapshot slab to the pool;
  // Notify() flips `done`. A caller woken in between would race the
  // loop thread for the very slabs its last cycle used (entry
  // snapshots, the fused buffer) and turn the steady state into misses.
  std::shared_ptr<HandleState> Stage(Entry& e, const char* data,
                                     long long nbytes,
                                     const std::vector<long long>* shape,
                                     const char* error,
                                     const char* copy_phase = nullptr) {
    std::shared_ptr<HandleState> hs;
    bool cancelled = false;
    bool already_done = false;  // deadline sweep released the waiter
    {
      std::lock_guard<std::mutex> g(mu_);
      auto pit = pending_names_.find(e.name);
      if (pit != pending_names_.end()) {
        already_done = pit->second.fired;
        if (pit->second.has_deadline && deadline_count_ > 0)
          deadline_count_--;
        // Completion instruments (twin of _complete in engine.py): final
        // phase residency, end-to-end submit->complete latency per op
        // class, and the remaining deadline margin (clipped >= 0 — a
        // late completion past its deadline reports zero margin).
        Clock::time_point now = Clock::now();
        ObservePhaseLocked(
            pit->second.phase,
            std::chrono::duration<double>(now - pit->second.phase_since)
                .count());
        ObserveCompleteLocked(
            e.op, std::chrono::duration<double>(now - e.enqueued).count(),
            e.priority);
        if (pit->second.has_deadline) {
          double margin =
              std::chrono::duration<double>(pit->second.deadline - now)
                  .count();
          ObserveInto(latency_.deadline_margin, &latency_.deadline_margin_sum,
                      margin > 0.0 ? margin : 0.0);
        }
        pending_names_.erase(pit);
      }
      // Cooperative cancel: an organic error outranks it (the waiter
      // gets the real failure); otherwise the completed/late result is
      // DISCARDED and the waiter sees the cancel error.
      cancelled = cancelled_.erase(e.handle) > 0 && error == nullptr;
      if (cancelled) stats_.cancelled++;
      // Counted whether or not the handle is still live (the Python twin
      // counts every completion the same way).
      if (error || cancelled) stats_.errors++; else stats_.completed++;
      // Release the admission reservation: every admitted entry passes
      // through Stage exactly once (success, error, cancel, shutdown).
      adm_inflight_[PriorityClass(e.priority)].fetch_sub(
          1, std::memory_order_relaxed);
      adm_bytes_[PriorityClass(e.priority)].fetch_sub(
          e.nbytes, std::memory_order_relaxed);
      auto it = handles_.find(e.handle);
      if (it != handles_.end()) {
        hs = it->second;
        already_done = already_done || hs->done;
      }
    }
    std::string cancel_msg;
    if (cancelled) {
      timeline_.Begin(e.name, "CANCELLED");
      timeline_.End(e.name, "CANCELLED");
      cancel_msg = "collective '" + e.name +
                   "' was cancelled (cooperative cancel; result "
                   "discarded)";
      error = cancel_msg.c_str();
    }
    // Batched members stamp batch_n on the QUEUE end so trace tools can
    // attribute the batch's queue share per member instead of N x.
    std::string qargs;
    if (e.batch_n > 1)
      qargs = "\"batch_n\": " + std::to_string(e.batch_n);
    if (hs != nullptr && already_done) {
      // The sweep already failed this waiter with its attributed
      // CollectiveTimeout — a late completion must neither clobber the
      // error nor re-notify (the sweep's write was the final one).
      timeline_.End(e.name, "QUEUE", qargs);
      hs = nullptr;
    } else if (hs != nullptr) {
      if (error) {
        hs->error = error;
      } else {
        bool trace_copy = copy_phase != nullptr;
        Clock::time_point t_copy = Clock::now();
        if (trace_copy) timeline_.Begin(e.name, copy_phase);
        // Result buffer from the pool (returned by ~HandleState once the
        // handle retires and the last waiter leaves).
        bool tracked = false;
        hs->result = pool_->Get(nbytes, &tracked);
        memcpy(hs->result.data(), data, (size_t)nbytes);
        if (shape) hs->shape = *shape;
        if (trace_copy) {
          timeline_.End(e.name, copy_phase,
                        BufferPool::PooledArgs(tracked));
          // Fused copy-out pass: native-only engine.phase.memcpy feed
          // (the python twin unpacks by view — no copy to time).
          ObserveMemcpy(SecondsSince(t_copy));
        }
      }
      timeline_.End(e.name, "QUEUE", qargs);
    }
    // Retire the entry's snapshot slab (donated buffers are caller-owned
    // and stay untouched). Batched snapshots park under their tensor
    // name so the next steady-state submit skips even the bucket scan.
    if (!e.ext && e.data.capacity()) {
      if (e.bound)
        pool_->PutBound(e.name, std::move(e.data));
      else
        pool_->Put(std::move(e.data));
    }
    return hs;
  }

  void Notify(const std::shared_ptr<HandleState>& hs) {
    if (hs == nullptr) return;
    {
      std::lock_guard<std::mutex> g(mu_);
      hs->done = true;
    }
    cv_done_.notify_all();
  }

  void Complete(Entry& e, const char* data, long long nbytes,
                const std::vector<long long>* shape, const char* error,
                const char* copy_phase = nullptr) {
    Notify(Stage(e, data, nbytes, shape, error, copy_phase));
  }

  // Remaining seconds to an entry's deadline at execution time (the
  // hvd_request.deadline_s the data plane sees): 0 = none; may be
  // negative when already overdue (the waiter has been failed and the
  // engine is finishing for coherence only).
  static double DeadlineRemaining(const Entry& e) {
    if (!e.has_deadline) return 0.0;
    return std::chrono::duration<double>(e.deadline - Clock::now()).count();
  }

  double BatchDeadlineRemaining(const std::vector<Entry*>& batch) {
    double best = 0.0;
    for (auto* e : batch) {
      if (!e->has_deadline) continue;
      double r = DeadlineRemaining(*e);
      if (best == 0.0 || r < best) best = r;
    }
    return best;
  }

  // Phase attribution for the deadline sweep (QUEUE -> NEGOTIATE_* ->
  // ALLREDUCE/...); `phase` must be a string literal (stored by ptr).
  void SetPhase(const std::string& name, const char* phase) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = pending_names_.find(name);
    if (it == pending_names_.end()) return;
    Clock::time_point now = Clock::now();
    ObservePhaseLocked(
        it->second.phase,
        std::chrono::duration<double>(now - it->second.phase_since).count());
    it->second.phase = phase;
    it->second.phase_since = now;
  }

  // Fail the waiter of every overdue entry with an attributed
  // CollectiveTimeout-shaped error naming the stuck phase, and stamp a
  // DEADLINE_EXCEEDED instant into the ring. Runs on the loop thread
  // each cycle and on the watchdog thread (the loop may be wedged
  // inside an executor call). Zero work while no entry has a deadline.
  void SweepDeadlines() {
    struct Fired {
      long long handle;
      std::string name;
      const char* phase;
      double age;
    };
    std::vector<Fired> fired;
    {
      std::lock_guard<std::mutex> g(mu_);
      // The watchdog may sweep while the loop thread is wedged inside an
      // executor call: ring batches carrying deadlines must be visible.
      FoldRingLocked();
      if (deadline_count_ <= 0) return;
      Clock::time_point now = Clock::now();
      for (auto& kv : pending_names_) {
        Pending& p = kv.second;
        if (p.has_deadline && !p.fired && now > p.deadline) {
          p.fired = true;
          fired.push_back(Fired{p.handle, kv.first, p.phase,
                                SecondsSince(p.enqueued)});
        }
      }
    }
    for (auto& f : fired) {
      // Instant BEFORE releasing the waiter (the python twin's order):
      // a woken synchronize may read the event ring immediately, and
      // the DEADLINE_EXCEEDED instant must already be in it.
      char args[96];
      snprintf(args, sizeof(args), "\"phase\": \"%s\", \"age_s\": %.3f",
               f.phase, f.age);
      timeline_.Instant(f.name, "DEADLINE_EXCEEDED", args);
      std::shared_ptr<HandleState> hs;
      {
        std::lock_guard<std::mutex> g(mu_);
        stats_.deadline_exceeded++;
        auto it = handles_.find(f.handle);
        if (it != handles_.end() && !it->second->done) {
          hs = it->second;
          char msg[512];
          snprintf(msg, sizeof(msg),
                   "collective '%s' exceeded its deadline after %.2fs "
                   "stuck in phase %s (the request is abandoned; a late "
                   "completion will be discarded)",
                   f.name.c_str(), f.age, f.phase);
          hs->error = msg;
          hs->done = true;
        }
      }
      if (hs != nullptr) cv_done_.notify_all();
    }
  }

  // Cancel/deadline cull before announce/execute: true when the entry
  // was retired locally (waiter released, nothing announced/executed).
  bool CullEntry(Entry& e) {
    bool cancelled, fired;
    {
      std::lock_guard<std::mutex> g(mu_);
      cancelled = cancelled_.count(e.handle) > 0;
      auto it = pending_names_.find(e.name);
      fired = it != pending_names_.end() && it->second.fired;
    }
    if (cancelled) {
      Complete(e, nullptr, 0, nullptr, nullptr);  // Stage -> cancel path
      return true;
    }
    if (fired) {
      Complete(e, nullptr, 0, nullptr,
               "collective exceeded its deadline before execution");
      return true;
    }
    return false;
  }

  // Reference: CheckForStalledTensors warns every 60 s about tensors stuck
  // in the table (operations.cc:1535-1581). Separate thread: the loop
  // thread may itself be inside a hung collective.
  void Watchdog() {
    double interval = stall_s_ > 0 ? stall_s_ / 5.0 : 1.0;
    Clock::time_point last_warn{};
    while (true) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        // Deadline enforcement for entries the loop thread cannot
        // reach (wedged inside the executor): tighten the tick while
        // any in-flight entry carries a deadline. The kick (set at
        // enqueue) breaks an already-started coarse sleep — the
        // tightened tick alone would only apply to the NEXT wait.
        double tick = deadline_count_ > 0 && interval > 0.05
                          ? 0.05 : interval;
        WaitFor(cv_, lk, tick,
                [&] { return shutdown_ || deadline_kick_; });
        if (shutdown_) return;
        deadline_kick_ = false;
      }
      SweepDeadlines();
      if (stall_s_ <= 0) continue;
      if (SecondsSince(last_warn) < stall_s_ && last_warn != Clock::time_point{})
        continue;
      std::string stalled;
      {
        // Scan every in-flight tensor (queued OR executing): the loop
        // thread may be stuck inside a hung collective — exactly the
        // condition to report.
        std::lock_guard<std::mutex> g(mu_);
        for (auto& kv : pending_names_) {
          if (SecondsSince(kv.second.enqueued) > stall_s_) {
            if (!stalled.empty()) stalled += ", ";
            stalled += kv.first;
          }
        }
      }
      if (!stalled.empty()) {
        last_warn = Clock::now();
        fprintf(stderr,
                "WARNING: One or more tensors were submitted to be reduced, "
                "gathered or broadcasted by subset of ranks and are waiting "
                "for remainder of ranks for more than %.0f seconds. Stalled "
                "ops: %s\n",
                stall_s_, stalled.c_str());
      }
    }
  }

  double cycle_s_;
  long long fusion_bytes_;
  double stall_s_;
  Timeline timeline_;
  // shared_ptr: HandleStates return their result buffers on destruction,
  // which may outlive a destroyed Engine (a straggling WaitMeta caller).
  std::shared_ptr<BufferPool> pool_;

  std::mutex mu_;
  std::condition_variable cv_, cv_done_;
  hvd_engine_stats stats_{};  // guarded by mu_
  hvd_engine_latency latency_{};  // guarded by mu_ (see GetLatency)
  std::deque<Entry> queue_;
  std::unordered_map<std::string, Pending> pending_names_;
  std::unordered_map<long long, std::shared_ptr<HandleState>> handles_;
  // Deadline/cancel plane (guarded by mu_): in-flight entries carrying
  // a deadline (the sweep's zero-cost short circuit) and handles with a
  // cooperative cancel pending.
  long long deadline_count_ = 0;
  bool deadline_kick_ = false;  // enqueue -> watchdog wake (under mu_)
  std::unordered_set<long long> cancelled_;
  // Atomic: the batched fast path reserves handles without mu_.
  std::atomic<long long> next_handle_{0};
  bool shutdown_ = false;
  // Lock-free mirror of shutdown_ for the submit fast path; the
  // post-publish recheck in EnqueueN plus Join's post-join fold close
  // the publish-vs-shutdown race (both sides are seq_cst).
  std::atomic<bool> shutdown_flag_{false};
  SubmitRing ring_;
  std::atomic<long long> ring_full_{0}, ring_spins_{0};
  // Serving-plane admission state (index = priority class code).
  // Atomics, not mu_: the batched submit fast path pre-checks and
  // reserves without the engine lock. Budgets are 0 = unlimited;
  // in-flight counts/bytes are incremented at admission and released
  // at Stage (or AdmitEntryLocked's fail path).
  std::atomic<long long> adm_max_inflight_[3]{};
  std::atomic<long long> adm_max_bytes_[3]{};
  std::atomic<long long> adm_inflight_[3]{};
  std::atomic<long long> adm_bytes_[3]{};
  std::atomic<long long> admission_rejected_{0}, admission_shed_{0};
  bool sort_by_name_ = false;
  hvd_exec_fn exec_fn_ = nullptr;
  void* exec_ctx_ = nullptr;
  hvd_negotiate_fn neg_fn_ = nullptr;
  void* neg_ctx_ = nullptr;
  bool neg_active_ = false;
  // Negotiation rounds started (guarded by mu_) — the inspect records'
  // `round` field; the python twin reads Coordinator.round.
  long long neg_round_ = 0;
  double extra_wait_ = 0.0;  // one-shot idle-round backoff
  // Loop-thread-only state (no lock needed):
  std::vector<Entry> negotiating_;
  bool neg_poisoned_ = false;
  std::string neg_poison_;

  std::thread loop_, watchdog_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C API (the shape of the reference's C API, operations.h:75-125)
// ---------------------------------------------------------------------------

extern "C" {

void* hvd_engine_create(double cycle_s, long long fusion_bytes,
                        double stall_s, const char* timeline_path) {
  return new Engine(cycle_s, fusion_bytes, stall_s, timeline_path);
}

void hvd_engine_set_executor(void* e, hvd_exec_fn fn, void* ctx) {
  static_cast<Engine*>(e)->SetExecutor(fn, ctx);
}

void hvd_engine_set_params(void* e, double cycle_s, long long fusion_bytes) {
  static_cast<Engine*>(e)->SetParams(cycle_s, fusion_bytes);
}

void hvd_engine_get_params(void* e, double* cycle_s, long long* fusion_bytes) {
  static_cast<Engine*>(e)->GetParams(cycle_s, fusion_bytes);
}

void hvd_engine_set_sort_by_name(void* e, int on) {
  static_cast<Engine*>(e)->SetSortByName(on);
}

void hvd_engine_set_admission(void* e, const long long* max_inflight,
                              const long long* max_bytes) {
  static_cast<Engine*>(e)->SetAdmission(max_inflight, max_bytes);
}

void hvd_engine_set_negotiator(void* e, hvd_negotiate_fn fn, void* ctx) {
  static_cast<Engine*>(e)->SetNegotiator(fn, ctx);
}

void hvd_engine_set_negotiation_active(void* e, int on) {
  static_cast<Engine*>(e)->SetNegotiationActive(on);
}

long long hvd_engine_enqueue(void* e, int op, const char* name, int dtype_num,
                             int itemsize, const void* data,
                             const long long* shape, int ndim, int average,
                             int root_rank, double prescale, int wire,
                             int wire_dcn, int donate, int priority,
                             double deadline_s, char* err) {
  return static_cast<Engine*>(e)->Enqueue(op, name, dtype_num, itemsize, data,
                                          shape, ndim, average, root_rank,
                                          prescale, wire, wire_dcn, donate,
                                          priority, deadline_s, err);
}

int hvd_engine_enqueue_n(void* e, hvd_request* reqs, int n,
                         long long* handles_out, char* err) {
  return static_cast<Engine*>(e)->EnqueueN(reqs, n, handles_out, err);
}

int hvd_engine_poll(void* e, long long handle) {
  return static_cast<Engine*>(e)->Poll(handle);
}

int hvd_engine_cancel(void* e, long long handle) {
  return static_cast<Engine*>(e)->Cancel(handle);
}

int hvd_engine_wait_meta(void* e, long long handle, long long* nbytes,
                         int* ndim, long long* shape8, char* err) {
  return static_cast<Engine*>(e)->WaitMeta(handle, nbytes, ndim, shape8, err);
}

int hvd_engine_copy_result(void* e, long long handle, void* out,
                           long long cap) {
  return static_cast<Engine*>(e)->CopyResult(handle, out, cap);
}

void hvd_engine_drop(void* e, long long handle) {
  static_cast<Engine*>(e)->Drop(handle);
}

long long hvd_engine_pending(void* e) {
  return static_cast<Engine*>(e)->PendingCount();
}

long long hvd_engine_pending_names(void* e, char* out, long long cap) {
  return static_cast<Engine*>(e)->PendingNames(out, cap);
}

long long hvd_engine_inspect(void* e, char* out, long long cap) {
  return static_cast<Engine*>(e)->Inspect(out, cap);
}

void hvd_engine_get_stats(void* e, hvd_engine_stats* out) {
  static_cast<Engine*>(e)->GetStats(out);
}

void hvd_engine_get_latency(void* e, hvd_engine_latency* out) {
  static_cast<Engine*>(e)->GetLatency(out);
}

void hvd_engine_timeline_instant(void* e, const char* name,
                                 const char* phase, const char* args) {
  static_cast<Engine*>(e)->TimelineInstant(name, phase, args);
}

void hvd_engine_timeline_meta(void* e, const char* name, const char* args) {
  static_cast<Engine*>(e)->TimelineMeta(name, args);
}

long long hvd_engine_timeline_now(void* e) {
  return static_cast<Engine*>(e)->TimelineNow();
}

long long hvd_engine_recent_events(void* e, char* out, long long cap) {
  return static_cast<Engine*>(e)->RecentEvents(out, cap);
}

void hvd_engine_shutdown(void* e) { static_cast<Engine*>(e)->Shutdown(); }

void hvd_engine_join(void* e) { static_cast<Engine*>(e)->Join(); }

void hvd_engine_destroy(void* e) { delete static_cast<Engine*>(e); }

}  // extern "C"
