"""ctypes binding + build for libhvdcore (the native engine).

Mirrors the reference's loader role (reference: horovod/common/__init__.py:
51-56 loads the C library RTLD_GLOBAL; setup.py builds it). Here the
library is a single translation unit built on demand with g++ — no MPI, no
framework headers — so it compiles anywhere in seconds and is cached next
to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "hvdcore.cc")
_LIB = os.path.join(_DIR, "libhvdcore.so")

_lock = threading.Lock()
_lib = None

# Default build promoted to -Wall -Wextra -Werror (hvdcheck satellite):
# the engine core compiles warning-clean, and a new warning is a build
# failure the commit it lands in, not reviewer homework.
_BASE_FLAGS = ["-std=c++17", "-fPIC", "-shared", "-pthread",
               "-Wall", "-Wextra", "-Werror"]

# HVD_SANITIZE={thread,address} rebuild modes. Each mode publishes its
# own artifact next to the source (the default lib is never clobbered
# by a sanitized build, so flipping the env var back costs nothing).
# -O1 -fno-omit-frame-pointer is the sanitizer-recommended pairing:
# usable stacks, tolerable slowdown.
_SANITIZE_MODES = {
    "": ([], _LIB, ["-O2", "-g"]),
    "thread": (["-fsanitize=thread", "-fno-omit-frame-pointer"],
               os.path.join(_DIR, "libhvdcore.tsan.so"), ["-O1", "-g"]),
    "address": (["-fsanitize=address", "-fno-omit-frame-pointer"],
                os.path.join(_DIR, "libhvdcore.asan.so"), ["-O1", "-g"]),
}

# TSan suppressions for the Python-hosted run (tests + LD_PRELOAD
# recipe in docs/static-analysis.md). The engine code itself must stay
# report-clean — these only quiet runtime noise from non-instrumented
# host code.
TSAN_SUPPRESSIONS = os.path.join(_DIR, "tsan.supp")


class NativeBuildError(RuntimeError):
    pass


def sanitize_mode() -> str:
    """The HVD_SANITIZE build mode ('', 'thread' or 'address'); unknown
    spellings fail fast rather than silently building unsanitized."""
    mode = os.environ.get("HVD_SANITIZE", "").strip().lower()
    if mode in ("0", "off", "none", "false"):
        mode = ""
    if mode not in _SANITIZE_MODES:
        raise NativeBuildError(
            f"unknown HVD_SANITIZE mode {mode!r}: expected 'thread' or "
            "'address'")
    return mode


def sanitizer_runtime(mode: str = "thread") -> str:
    """Path to the sanitizer runtime to LD_PRELOAD when loading a
    sanitized libhvdcore into an UNinstrumented interpreter (loading it
    bare fails with a static-TLS error). Resolved through the same
    compiler that builds the library."""
    name = {"thread": "libtsan.so", "address": "libasan.so"}[mode]
    proc = subprocess.run(["g++", f"-print-file-name={name}"],
                          capture_output=True, text=True)
    path = proc.stdout.strip()
    if proc.returncode != 0 or not os.path.exists(path):
        raise NativeBuildError(f"cannot locate {name} via g++")
    return os.path.realpath(path)


def build_library(force: bool = False, mode: Optional[str] = None) -> str:
    """Compile the engine library if missing or stale; returns the path.
    ``mode`` overrides HVD_SANITIZE ('' = the plain production build)."""
    mode = sanitize_mode() if mode is None else mode
    san_flags, out, opt_flags = _SANITIZE_MODES[mode]
    with _lock:
        if (not force and os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(_SRC)):
            return out
        # pid-suffixed temp: concurrent processes (multi-controller first
        # run on a shared filesystem) must not compile into the same file;
        # os.replace makes the final publish atomic whoever wins.
        tmp = f"{out}.tmp.{os.getpid()}.so"
        cmd = (["g++"] + opt_flags + _BASE_FLAGS + san_flags
               + [_SRC, "-o", tmp])
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"failed to build libhvdcore: {proc.stderr[-2000:]}")
        os.replace(tmp, out)
        return out


_SHIELD_SRC = os.path.join(_DIR, "termshield.cc")
_SHIELD_LIB = os.path.join(_DIR, "libtermshield.so")
_shield_lib = None


def load_termshield():
    """Build + load the std::terminate parking shim (see termshield.cc)
    and install it. Elastic-only callers; raises NativeBuildError when
    the toolchain is unavailable. Cached + idempotent."""
    global _shield_lib
    with _lock:
        if _shield_lib is not None:
            return _shield_lib
        if not (os.path.exists(_SHIELD_LIB)
                and os.path.getmtime(_SHIELD_LIB)
                >= os.path.getmtime(_SHIELD_SRC)):
            tmp = f"{_SHIELD_LIB}.tmp.{os.getpid()}.so"
            cmd = (["g++", "-O2"] + _BASE_FLAGS
                   + [_SHIELD_SRC, "-o", tmp, "-ldl"])
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(
                    f"failed to build termshield: {proc.stderr[-2000:]}")
            os.replace(tmp, _SHIELD_LIB)
        lib = ctypes.CDLL(_SHIELD_LIB)
        lib.hvd_termshield_install.argtypes = []
        lib.hvd_termshield_install()
        _shield_lib = lib
        return lib


class HvdRequest(ctypes.Structure):
    _fields_ = [
        ("op", ctypes.c_int),
        ("dtype_num", ctypes.c_int),
        ("itemsize", ctypes.c_int),
        ("average", ctypes.c_int),
        ("root_rank", ctypes.c_int),
        # Engine wire policy code (core/engine.py WIRE_CODES).
        ("wire", ctypes.c_int),
        # Per-tier DCN policy code (hierarchical two-phase route) —
        # mutually exclusive with a nonzero `wire`.
        ("wire_dcn", ctypes.c_int),
        ("prescale", ctypes.c_double),
        # Seconds to the request's deadline at executor-call time (0 =
        # none; negative = already overdue — enforcement is the engine
        # loop/watchdog's, this is data-plane advice only).
        ("deadline_s", ctypes.c_double),
        ("names", ctypes.c_char_p),
        ("data", ctypes.c_void_p),
        # Where same-size results must be written: == data unless the
        # input was DONATED (caller-owned, read-only to the engine), in
        # which case the engine supplies a pooled bounce buffer.
        ("out", ctypes.c_void_p),
        ("count", ctypes.c_longlong),
        ("ndim", ctypes.c_int),
        ("shape", ctypes.c_longlong * 8),
        # Batched-submit plane (hvd_engine_enqueue_n): per-request
        # ownership-handoff flag, honored element-by-element like the
        # single-enqueue `donate` argument. Engine->executor requests
        # always carry 0 here.
        ("donate", ctypes.c_int),
        # Priority class code (core/engine.py PRIORITY_CODES; lower
        # drains first) — the serving-plane scheduling key.
        ("priority", ctypes.c_int),
    ]


class HvdResult(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("nbytes", ctypes.c_longlong),
        ("ndim", ctypes.c_int),
        ("shape", ctypes.c_longlong * 8),
        # Executor-measured host->device staging seconds; the engine turns
        # it into the WAIT_FOR_DATA timeline span.
        ("stage_s", ctypes.c_double),
        # Bytes the mesh collective shipped (payload+scales under a
        # quantized wire policy) and the compressed-policy subset.
        ("wire_bytes", ctypes.c_longlong),
        ("wire_compressed", ctypes.c_longlong),
        # Per-tier byte split of the hierarchical two-phase route (zero
        # on flat routes): DCN = quantized 1/L cross-tier payload, ICI =
        # full-width intra-tier share.
        ("wire_dcn", ctypes.c_longlong),
        ("wire_ici", ctypes.c_longlong),
        ("error", ctypes.c_char * 256),
    ]


class HvdStats(ctypes.Structure):
    """Execution-side telemetry snapshot — field layout MUST stay in sync
    with hvd_engine_stats in hvdcore.cc."""

    _fields_ = [
        ("submitted", ctypes.c_longlong * 3),
        ("submitted_bytes", ctypes.c_longlong),
        ("completed", ctypes.c_longlong),
        ("errors", ctypes.c_longlong),
        ("fused_batches", ctypes.c_longlong),
        ("fused_tensors", ctypes.c_longlong),
        ("fused_bytes", ctypes.c_longlong),
        ("cycles", ctypes.c_longlong),
        ("cycle_seconds", ctypes.c_double),
        ("queue_depth", ctypes.c_longlong),
        ("wire_bytes", ctypes.c_longlong),
        ("wire_bytes_compressed", ctypes.c_longlong),
        # Per-tier split of the hierarchical route (engine.wire_bytes
        # .dcn/.ici counter parity with the python engine).
        ("wire_bytes_dcn", ctypes.c_longlong),
        ("wire_bytes_ici", ctypes.c_longlong),
        # Buffer-pool accounting (hvdcore BufferPool — fed into the same
        # engine.pool.* telemetry the python pool feeds).
        ("pool_hits", ctypes.c_longlong),
        ("pool_misses", ctypes.c_longlong),
        ("pool_checkouts", ctypes.c_longlong),
        ("pool_bytes_resident", ctypes.c_longlong),
        # Deadline/cancel plane (engine.deadline_exceeded /
        # engine.cancelled counter parity with the python engine).
        ("deadline_exceeded", ctypes.c_longlong),
        ("cancelled", ctypes.c_longlong),
        # Batched-submit plane: submit-ring pressure and name-bound pool
        # reuse (engine.ring.{full,spins} / engine.pool.bound_hits).
        ("ring_full", ctypes.c_longlong),
        ("ring_spins", ctypes.c_longlong),
        ("pool_bound_hits", ctypes.c_longlong),
        # Serving-plane admission control (engine.admission.* counter/
        # gauge parity with the python engine): boundary rejections,
        # deadline-aware sheds, and per-class in-flight counts.
        ("admission_rejected", ctypes.c_longlong),
        ("admission_shed", ctypes.c_longlong),
        ("admission_inflight_high", ctypes.c_longlong),
        ("admission_inflight_normal", ctypes.c_longlong),
        ("admission_inflight_low", ctypes.c_longlong),
        ("admission_bytes_high", ctypes.c_longlong),
        ("admission_bytes_normal", ctypes.c_longlong),
        ("admission_bytes_low", ctypes.c_longlong),
    ]


class HvdLatency(ctypes.Structure):
    """Latency/phase-residency histogram snapshot — field layout MUST
    stay in sync with hvd_engine_latency in hvdcore.cc. Each instrument
    is 13 raw bucket counts over the shared LATENCY_BUCKETS_S edges
    (last = +Inf overflow) plus an exact value sum; native_engine.py
    folds count deltas into the registry via Histogram.add_counts."""

    _fields_ = [
        ("allreduce", ctypes.c_longlong * 13),
        ("allgather", ctypes.c_longlong * 13),
        ("broadcast", ctypes.c_longlong * 13),
        ("phase_queue", ctypes.c_longlong * 13),
        ("phase_negotiate", ctypes.c_longlong * 13),
        ("phase_memcpy", ctypes.c_longlong * 13),
        ("phase_exec", ctypes.c_longlong * 13),
        ("deadline_margin", ctypes.c_longlong * 13),
        # Per-priority-class serving-plane latency split
        # (engine.latency.class.* histogram parity).
        ("class_high", ctypes.c_longlong * 13),
        ("class_normal", ctypes.c_longlong * 13),
        ("class_low", ctypes.c_longlong * 13),
        ("allreduce_sum", ctypes.c_double),
        ("allgather_sum", ctypes.c_double),
        ("broadcast_sum", ctypes.c_double),
        ("phase_queue_sum", ctypes.c_double),
        ("phase_negotiate_sum", ctypes.c_double),
        ("phase_memcpy_sum", ctypes.c_double),
        ("phase_exec_sum", ctypes.c_double),
        ("deadline_margin_sum", ctypes.c_double),
        ("class_high_sum", ctypes.c_double),
        ("class_normal_sum", ctypes.c_double),
        ("class_low_sum", ctypes.c_double),
    ]


EXEC_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                           ctypes.POINTER(HvdRequest),
                           ctypes.POINTER(HvdResult))

# Negotiation control-plane hook: (ctx, table_json, decision_out) -> rc.
# The callback must write an hvd_alloc()'d C string into *decision_out.
NEG_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
                          ctypes.POINTER(ctypes.c_void_p))


def load_library():
    """Build if needed, load, and declare signatures. Cached."""
    global _lib
    if _lib is not None:
        return _lib
    path = build_library()
    lib = ctypes.CDLL(path)
    lib.hvd_engine_create.restype = ctypes.c_void_p
    lib.hvd_engine_create.argtypes = [ctypes.c_double, ctypes.c_longlong,
                                      ctypes.c_double, ctypes.c_char_p]
    lib.hvd_engine_set_executor.argtypes = [ctypes.c_void_p, EXEC_FN,
                                            ctypes.c_void_p]
    lib.hvd_engine_set_params.argtypes = [ctypes.c_void_p, ctypes.c_double,
                                          ctypes.c_longlong]
    lib.hvd_engine_get_params.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_longlong)]
    lib.hvd_engine_set_sort_by_name.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int]
    lib.hvd_engine_set_negotiator.argtypes = [ctypes.c_void_p, NEG_FN,
                                              ctypes.c_void_p]
    lib.hvd_engine_set_negotiation_active.argtypes = [ctypes.c_void_p,
                                                      ctypes.c_int]
    lib.hvd_alloc.restype = ctypes.c_void_p
    lib.hvd_alloc.argtypes = [ctypes.c_longlong]
    lib.hvd_engine_enqueue.restype = ctypes.c_longlong
    lib.hvd_engine_enqueue.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_double,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_double, ctypes.c_char_p]
    lib.hvd_engine_set_admission.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong)]
    lib.hvd_engine_enqueue_n.restype = ctypes.c_int
    lib.hvd_engine_enqueue_n.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(HvdRequest), ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_char_p]
    lib.hvd_engine_poll.restype = ctypes.c_int
    lib.hvd_engine_poll.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.hvd_engine_cancel.restype = ctypes.c_int
    lib.hvd_engine_cancel.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.hvd_engine_wait_meta.restype = ctypes.c_int
    lib.hvd_engine_wait_meta.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_char_p]
    lib.hvd_engine_copy_result.restype = ctypes.c_int
    lib.hvd_engine_copy_result.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
        ctypes.c_longlong]
    lib.hvd_engine_drop.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.hvd_engine_pending.restype = ctypes.c_longlong
    lib.hvd_engine_pending.argtypes = [ctypes.c_void_p]
    lib.hvd_engine_pending_names.restype = ctypes.c_longlong
    lib.hvd_engine_pending_names.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.hvd_engine_inspect.restype = ctypes.c_longlong
    lib.hvd_engine_inspect.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.hvd_engine_get_stats.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(HvdStats)]
    lib.hvd_engine_get_latency.argtypes = [ctypes.c_void_p,
                                           ctypes.POINTER(HvdLatency)]
    lib.hvd_engine_timeline_instant.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    lib.hvd_engine_timeline_meta.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
    lib.hvd_engine_timeline_now.restype = ctypes.c_longlong
    lib.hvd_engine_timeline_now.argtypes = [ctypes.c_void_p]
    lib.hvd_engine_recent_events.restype = ctypes.c_longlong
    lib.hvd_engine_recent_events.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.hvd_engine_shutdown.argtypes = [ctypes.c_void_p]
    lib.hvd_engine_join.argtypes = [ctypes.c_void_p]
    lib.hvd_engine_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib
