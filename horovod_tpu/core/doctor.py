"""The hang doctor: cross-rank stall diagnosis with attributed verdicts.

The engines' watchdogs say *that* a rank is stuck (stall warnings,
``CollectiveTimeout``/``NegotiationTimeout``, the straggler report says
who was *historically* slow) — this module names *which tensor* is
wedging the world, *which ranks never announced it*, and *why*, the seat
the reference fills with ``CheckForStalledTensors`` (SURVEY C6), made
automatic instead of a human diffing eight flight dumps.

Flow
----
1. On a hang-class flight dump (stall / deadline / negotiation /
   SIGUSR1) or on-demand ``hvd.diagnose()``, each rank snapshots its
   engine's full per-entry inspect table (``Engine.inspect`` /
   ``hvd_engine_inspect`` — identical record shape, hvdcheck rule
   ``parity-doctor``) and publishes it under an epoch-scoped key on the
   existing fleet/KV plane (``hvd/doctor/g{g}/e{e}/p{rank}``).
2. The diagnoser — every stalled rank live, or offline over flight
   dumps (``stats --doctor``) — merges whatever snapshots are visible
   and computes the cross-rank submission diff.
3. The verdict is attributed with a FIXED classification vocabulary
   (``VERDICT_KINDS`` — the cross-surface parity contract with
   ``utils/stats``): it rides the triggering flight dump, feeds the
   sentinel as verdict kind ``hang`` (``/healthz`` degrades), serves on
   the telemetry endpoint's ``/doctor`` arm, and blames a tensor on the
   fleet ``--watch`` console.

Everything here is post-mortem tooling: no function on the engine path
may raise out of this module.
"""

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.core import telemetry as tele

# The fixed classification vocabulary, in attribution-priority order
# (the first kind found becomes the verdict's primary ``kind``). This
# tuple is machine-diffed against the ``_DOCTOR_KINDS`` consumer table
# in utils/stats.py by hvdcheck rule ``parity-doctor`` — rename a kind
# on either side and the analysis names the skew.
VERDICT_KINDS = (
    "dead_peer",           # a missing rank has an elastic death note
    "draining",            # a missing/quiesced rank is deliberately draining
    "overload",            # a rank's admission budget is tripped (serving plane)
    "missing_submitter",   # tensor + the exact ranks that never announced it
    "metadata_mismatch",   # per-rank shape/dtype/wire skew on one name
    "slow_executor",       # phase age far beyond the phase-latency median
    "kv_degraded",         # the coordination KV store failed over
)

# Dump kinds that engage the doctor (the engines tag their hang-class
# flight dumps with these; anything else dumps without a diagnosis).
HANG_KINDS = ("stall", "deadline", "negotiation", "sigusr1", "diagnose")

# An exec-phase entry is "slow" past max(this multiple of the local
# engine.phase.exec median, the absolute floor) — generous on purpose:
# the doctor must not cry slow_executor over ordinary jitter.
SLOW_MULTIPLE = 10.0
SLOW_FLOOR_US = 1_000_000.0


def doctor_key(generation: int, epoch: int, rank: int) -> str:
    return f"hvd/doctor/g{generation}/e{epoch}/p{rank}"


def _world_coords() -> Tuple[int, int]:
    from horovod_tpu.core import fleet

    return fleet._world_coords()


def _rank_nproc(rank: Optional[int]) -> Tuple[int, int]:
    try:
        from horovod_tpu.common import topology as topo

        if topo.is_initialized():
            return (topo.process_index() if rank is None else int(rank),
                    topo.num_processes())
    except Exception:  # pragma: no cover - defensive
        pass
    return (0 if rank is None else int(rank)), 1


def _dead_ranks() -> Dict[int, str]:
    """Elastic death notes (rank -> reason) — a missing submitter that
    is KNOWN dead earns ``dead_peer``, not ``missing_submitter``."""
    try:
        from horovod_tpu.core import elastic

        summary = elastic.world_summary()
        if summary:
            return {int(r): str(why)
                    for r, why in summary.get("dead", {}).items()}
    except Exception:  # pragma: no cover - defensive
        pass
    return {}


def _draining_reason() -> Optional[str]:
    try:
        from horovod_tpu.core import sentinel

        return sentinel._draining_reason()
    except Exception:  # pragma: no cover - defensive
        return None


def _admission_state() -> Optional[dict]:
    """This rank's serving-plane admission snapshot (both engines
    produce the same shape via core/engine.py build_admission_summary),
    or None before any engine exists."""
    try:
        from horovod_tpu.core import engine as _eng

        return _eng.admission_summary()
    except Exception:  # pragma: no cover - defensive
        return None


def _kv_failovers() -> int:
    try:
        return int(tele.REGISTRY.counter("world.kv_failovers").snapshot())
    except Exception:  # pragma: no cover - defensive
        return 0


def _exec_median_us() -> Optional[float]:
    """Local ``engine.phase.exec`` median (the PR 17 phase-latency
    instrument, fed by BOTH engines) — the slow_executor yardstick."""
    try:
        h = tele.REGISTRY.histogram_counts().get("engine.phase.exec")
        if not h or not h.get("count"):
            return None
        v = tele.quantile_from_buckets(
            list(tele.LATENCY_BUCKETS_S), h["counts"], 0.5)
        return None if v is None else v * 1e6
    except Exception:  # pragma: no cover - defensive
        return None


def local_snapshot(table: List[dict], rank: Optional[int] = None,
                   kind: Optional[str] = None,
                   reason: Optional[str] = None) -> dict:
    """This rank's published view: the inspect table plus the local
    context the classifier attributes with (drain marker, KV failover
    count, the phase-latency median)."""
    rank, nproc = _rank_nproc(rank)
    g, e = _world_coords()
    return {
        "v": 1,
        "rank": int(rank),
        "nproc": int(nproc),
        "wall": time.time(),
        "generation": int(g),
        "epoch": int(e),
        "kind": kind,
        "reason": (str(reason).splitlines()[0][:300]
                   if reason is not None else None),
        "entries": list(table or []),
        "draining": _draining_reason(),
        "admission": _admission_state(),
        "kv_failovers": _kv_failovers(),
        "exec_median_us": _exec_median_us(),
    }


def _kv():
    """The fleet plane's KV handle (FileKV over the shared fleet
    directory), or None when the plane is off — the doctor then degrades
    to a one-rank diagnosis."""
    try:
        from horovod_tpu.core import fleet

        d = fleet.fleet_dir()
        if not d or not fleet.enabled():
            return None
        from horovod_tpu.core.elastic import FileKV

        return FileKV(d)
    except Exception:  # pragma: no cover - defensive
        return None


def publish(kv, snap: dict):
    """One snapshot to the epoch-scoped doctor key. Same durability
    policy as the fleet publisher: rename-only (durable=False) — a
    snapshot lost to power failure is just a missing peer view."""
    key = doctor_key(snap["generation"], snap["epoch"], snap["rank"])
    try:
        kv.set(key, json.dumps(snap), durable=False)
    except TypeError:
        # KV backends without the durability knob (LocalKV in tests).
        kv.set(key, json.dumps(snap))


def collect(kv, generation: int, epoch: int, nproc: int,
            exclude: Optional[int] = None) -> List[dict]:
    """Peer snapshots for the current (generation, epoch) — non-blocking
    reads; a rank that never published (wedged before its dump, dead,
    or simply not stalled) is just absent and becomes part of the
    diagnosis."""
    snaps: List[dict] = []
    for rank in range(int(nproc)):
        if rank == exclude:
            continue
        raw = None
        try:
            raw = kv.try_get(doctor_key(generation, epoch, rank))
        except Exception:  # a failing KV must not wedge the diagnosis
            continue
        if raw is None:
            continue
        try:
            snaps.append(json.loads(raw))
        except ValueError:
            continue  # torn/foreign value: skip, never raise
    return snaps


def _is_exec_phase(phase: str) -> bool:
    return bool(phase) and phase != "QUEUE" \
        and not str(phase).startswith("NEGOTIATE")


def classify(snaps: List[dict], nproc: Optional[int] = None,
             dead: Optional[Dict[int, str]] = None) -> dict:
    """The cross-rank submission diff → an attributed verdict.

    ``snaps`` is whatever per-rank snapshots are visible (live KV reads
    or offline flight dumps); ``nproc`` the world size the diff runs
    against (defaults to the largest size any snapshot reports);
    ``dead`` the elastic death notes. Returns a verdict dict whose
    ``kind`` is the highest-priority finding's (``VERDICT_KINDS``
    order), or None-kinded when nothing is attributable — classification
    itself never raises on malformed snapshots, it skips them."""
    dead = dict(dead or {})
    clean: List[dict] = []
    for s in snaps:
        try:
            int(s["rank"])
            clean.append(s)
        except Exception:
            continue
    # Newest snapshot per rank wins (offline dirs hold history).
    by_rank: Dict[int, dict] = {}
    for s in clean:
        r = int(s["rank"])
        prev = by_rank.get(r)
        if prev is None or s.get("wall", 0) >= prev.get("wall", 0):
            by_rank[r] = s
    if nproc is None:
        sizes = [int(s.get("nproc", 0)) for s in by_rank.values()]
        nproc = max(sizes + [len(by_rank)]) if by_rank else 0
    all_ranks = set(range(int(nproc))) | set(by_rank)
    draining_ranks = {r: s.get("draining") for r, s in by_rank.items()
                      if s.get("draining")}

    # name -> {rank: inspect record}
    tensors: Dict[str, Dict[int, dict]] = {}
    for r, s in by_rank.items():
        for rec in s.get("entries") or []:
            try:
                tensors.setdefault(str(rec["name"]), {})[r] = rec
            except Exception:
                continue

    findings: List[dict] = []
    blamed_dead: Dict[int, List[str]] = {}
    for name in sorted(tensors):
        submitters = set(tensors[name])
        missing = sorted(all_ranks - submitters)
        dead_missing = [r for r in missing if r in dead]
        drain_missing = [r for r in missing if r in draining_ranks]
        other = [r for r in missing
                 if r not in dead and r not in draining_ranks]
        for r in dead_missing:
            blamed_dead.setdefault(r, []).append(name)
        for r in drain_missing:
            findings.append({
                "kind": "draining", "tensor": name, "ranks": [r],
                "detail": f"rank {r} is draining "
                          f"({draining_ranks[r]}) and will not submit "
                          f"'{name}'"})
        if other:
            findings.append({
                "kind": "missing_submitter", "tensor": name,
                "ranks": other,
                "detail": f"rank(s) {other} never announced '{name}' "
                          f"(submitted by rank(s) "
                          f"{sorted(submitters)})"})
        if len(submitters) >= 2:
            meta = {r: (tensors[name][r].get("op"),
                        tensors[name][r].get("bytes"),
                        tensors[name][r].get("dtype"),
                        tensors[name][r].get("wire"))
                    for r in sorted(submitters)}
            if len(set(meta.values())) > 1:
                findings.append({
                    "kind": "metadata_mismatch", "tensor": name,
                    "ranks": sorted(submitters),
                    "detail": "per-rank (op, bytes, dtype, wire) skew: "
                              + "; ".join(
                                  f"rank {r}={list(v)}"
                                  for r, v in meta.items())})
    for r, names in sorted(blamed_dead.items()):
        findings.append({
            "kind": "dead_peer", "tensor": names[0], "ranks": [r],
            "detail": f"rank {r} is dead ({dead[r]}); it never "
                      f"announced {names}"})
    # A draining rank explains a stall even when no per-tensor diff
    # pinned it (its peers may not have published).
    for r, why in sorted(draining_ranks.items()):
        if not any(f["kind"] == "draining" and f["ranks"] == [r]
                   for f in findings):
            findings.append({
                "kind": "draining", "tensor": None, "ranks": [r],
                "detail": f"rank {r} is draining: {why}"})
    # overload: a rank whose admission budget is tripped right now — the
    # engine there is load-shedding, so a peer waiting on its submission
    # sees a stall that is really serving-plane saturation. The verdict
    # names the class and the budget so the fix is one knob away.
    for r, s in sorted(by_rank.items()):
        adm = s.get("admission") or {}
        trip = adm.get("tripped")
        if trip:
            cls = trip.get("cls")
            info = (adm.get("classes") or {}).get(cls) or {}
            findings.append({
                "kind": "overload", "tensor": None, "ranks": [r],
                "detail": f"rank {r} is overloaded: priority class "
                          f"'{cls}' tripped its {trip.get('budget')} "
                          f"admission budget "
                          f"({info.get('inflight')} in flight, queue "
                          f"depth {adm.get('queue_depth')}) — new "
                          "submits in that class are being rejected"})
    # slow_executor: an exec-phase entry far beyond the local median.
    for r, s in sorted(by_rank.items()):
        median = s.get("exec_median_us")
        if not median:
            continue
        threshold = max(SLOW_MULTIPLE * float(median), SLOW_FLOOR_US)
        for rec in s.get("entries") or []:
            try:
                if _is_exec_phase(rec.get("phase")) \
                        and float(rec.get("phase_age_us", 0)) > threshold:
                    findings.append({
                        "kind": "slow_executor",
                        "tensor": str(rec["name"]), "ranks": [r],
                        "detail": f"rank {r} has '{rec['name']}' in "
                                  f"phase {rec.get('phase')} for "
                                  f"{float(rec['phase_age_us']) / 1e6:.1f}s"
                                  f" (median "
                                  f"{float(median) / 1e6:.3f}s)"})
            except Exception:
                continue
    kv_ranks = {r: int(s.get("kv_failovers") or 0)
                for r, s in by_rank.items()
                if int(s.get("kv_failovers") or 0) > 0}
    if kv_ranks:
        findings.append({
            "kind": "kv_degraded", "tensor": None,
            "ranks": sorted(kv_ranks),
            "detail": "coordination KV store failed over on rank(s) "
                      + ", ".join(f"{r} (x{n})"
                                  for r, n in sorted(kv_ranks.items()))})

    primary = None
    for kind in VERDICT_KINDS:
        for f in findings:
            if f["kind"] == kind:
                primary = f
                break
        if primary is not None:
            break
    return {
        "v": 1,
        "kind": primary["kind"] if primary else None,
        "tensor": primary.get("tensor") if primary else None,
        "ranks": primary.get("ranks") if primary else None,
        "detail": primary.get("detail") if primary else None,
        "findings": findings,
        "ranks_reporting": sorted(by_rank),
        "nproc": int(nproc),
        "wall_us": int(time.time() * 1e6),
    }


_last_verdict: Optional[dict] = None


def last_verdict() -> Optional[dict]:
    """The most recent diagnosis this process produced (the ``/doctor``
    endpoint serves it between hangs), or None."""
    return _last_verdict


def on_hang(reason: Optional[str], kind: Optional[str],
            table: Optional[List[dict]],
            rank: Optional[int] = None) -> Optional[dict]:
    """The engines' hook on a hang-class flight dump: publish this
    rank's inspect snapshot, diagnose over whatever peer snapshots are
    visible, feed the sentinel. Returns the verdict (embedded in the
    triggering dump) or None when the dump kind does not engage the
    doctor. Raising is the caller's problem to swallow
    (``engine.doctor_on_hang``) — but nothing here blocks."""
    global _last_verdict
    if kind not in HANG_KINDS:
        return None
    snap = local_snapshot(table or [], rank=rank, kind=kind,
                          reason=reason)
    kv = _kv()
    snaps = [snap]
    if kv is not None:
        publish(kv, snap)
        snaps += collect(kv, snap["generation"], snap["epoch"],
                         snap["nproc"], exclude=snap["rank"])
    verdict = classify(snaps, nproc=snap["nproc"], dead=_dead_ranks())
    verdict["trigger"] = kind
    if (verdict.get("kind") is None and kind != "diagnose"
            and _last_verdict is not None
            and _last_verdict.get("kind") is not None):
        # An automatic hang signal that could not attribute anything
        # (a poisoned engine keeps re-dumping empty rounds after the
        # victims were culled) must not amnesia the standing diagnosis:
        # ``last_verdict``/``/doctor`` keep the attributed one. Only an
        # explicit ``hvd.diagnose()`` all-clear replaces it.
        return verdict
    _last_verdict = verdict
    if verdict.get("kind") is not None:
        try:
            from horovod_tpu.core import sentinel

            sentinel.note_hang(verdict, snap["rank"])
        except Exception:  # pragma: no cover - defensive
            pass
    return verdict


def diagnose() -> dict:
    """On-demand diagnosis (``hvd.diagnose()``): snapshot the live
    engine's inspect table, publish it, and diff against every visible
    peer snapshot — the FIRST rung of the hung-collective recovery
    ladder (docs/troubleshooting.md). Safe on a healthy world: an empty
    table simply announces "this rank is waiting on nothing"."""
    table: List[dict] = []
    try:
        from horovod_tpu.core import engine as _eng

        e = _eng._engine
        if e is not None:
            table = e.inspect()
    except Exception:
        table = []
    verdict = on_hang("on-demand hvd.diagnose()", "diagnose", table)
    return verdict if verdict is not None else classify([])


def diagnose_dumps(paths: List[str]) -> dict:
    """Offline diagnosis over hang-triggered flight-dump files (each
    embeds the rank's inspect table): the ``stats --doctor <dir>``
    backend. Dumps without an inspect table (non-hang kinds, pre-doctor
    versions) are skipped; the newest snapshot per rank wins."""
    snaps: List[dict] = []
    for path in paths:
        try:
            with open(path) as fh:
                payload = json.load(fh)
        except (OSError, ValueError):
            continue
        if "inspect" not in payload:
            continue
        telem = payload.get("telemetry") or {}
        snaps.append({
            "v": 1,
            "rank": int(payload.get("rank") or 0),
            "nproc": 0,
            "wall": float(payload.get("wall_us", 0)) / 1e6,
            "kind": payload.get("kind"),
            "reason": payload.get("reason"),
            "entries": payload.get("inspect") or [],
            "draining": None,
            "admission": None,
            "kv_failovers": int(telem.get("world.kv_failovers", 0)),
            "exec_median_us": None,
        })
    return classify(snaps)


def flight_dump_paths(directory: str) -> List[str]:
    """Every flight-dump file under ``directory`` (the
    ``hvd_flight.rank{N}.*`` spelling both dump writers use)."""
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names
            if n.startswith("hvd_flight.") and n.endswith(".json")]
