"""Cross-controller negotiation — the TPU-native coordinator protocol.

The reference coordinates multi-process collectives through a rank-0
master: every tick workers ``MPI_Gather`` their pending-request lists to
rank 0, which validates them, decides readiness + fusion, and
``MPI_Bcast``s a response all ranks then execute (reference:
horovod/common/operations.cc:279-517 ConstructMPIResponse, fusion decision
:2035-2074). On TPU there is no MPI; the idiomatic control plane is the
key-value store of the JAX coordination service (``jax.distributed``),
which every multi-controller run already stands up.

Protocol (one *round* per engine cycle, symmetric — no master):

1. Every process publishes ``<ns>/r<N>/p<pid>`` = JSON of its pending
   request metadata (name, op, dtype, shape, flags). Process 0's message
   additionally carries the engine params (cycle time, fusion threshold)
   — the role ParameterManager::SyncParams plays in the reference.
2. Every process reads all P round-``N`` keys (blocking, timeout).
3. Every process computes the SAME decision with a pure function of the
   identical inputs: a tensor is *ready* when every process announced it;
   announced-by-all tensors with mismatched fingerprints become error
   groups (the reference's ERROR response — surfaced on every process);
   ready tensors execute in lexicographic name order, allreduces fused
   per (dtype, average, prescale) up to the agreed threshold.

Rank-0 decision-making is unnecessary because the KV store gives every
process the same inputs — determinism replaces the broadcast. Entries not
yet announced everywhere simply stay pending for the next round, which is
also what powers missing-rank stall attribution (reference:
CheckForStalledTensors, operations.cc:1535-1581): every round each process
sees exactly who has NOT yet submitted a stalled tensor.

Cleanup: after completing round ``N`` a process deletes every consumed
round key it still owns (everyone publishing round ``N`` proves all
rounds ``< N`` were fully consumed). Shutdown publishes a tombstone key
peers poll while blocked, so a clean exit propagates as ``ShutdownError``
instead of a hang (reference: shutdown flag in MPIRequestList,
operations.cc:2008-2011).

Response cache (reference: horovod/common/response_cache.cc, the
optimization arxiv 1802.05799 + the MPI-coordination study 1810.11112
motivate — per-tensor negotiation dominates small-tensor overhead at
scale): a training loop submits the SAME tensor set thousands of times,
so each process keeps a capacity-bounded LRU of previously-agreed
request identities (:class:`ResponseCache`). When every entry of a
round hits the cache on every process, the round degrades to exchanging
one compact bitvector (+ cache-epoch) instead of the full wire tables,
and ``decide()`` is skipped for a memoized group composition. Coherence
is lockstep by construction: cache mutations (inserts, recency, LRU
evictions) happen only from round data every process observes
identically, and the epoch carried by every message detects any
divergence — on mismatch ALL processes complete the round with nothing
scheduled, clear their caches, and resynchronize on the next full-table
round, so a stale hit is structurally impossible.
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import random
import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.core import faultline as _flt
from horovod_tpu.core import telemetry as _tele

LOG = logging.getLogger("horovod_tpu.coordinator")

# Blocked-read poll slices grow with jittered exponential backoff from
# _POLL_SLICE_MIN_S up to HVD_KV_POLL_MAX: long waits (a genuinely slow
# peer) stop hammering the KV store with fixed-interval probes, while the
# first slices stay short so quick rounds keep their latency. The jitter
# de-synchronizes P processes' probe trains against one coordination
# service.
_POLL_SLICE_MIN_S = 0.1
_POLL_SLICE_MAX_S = float(os.environ.get("HVD_KV_POLL_MAX", "2.0"))
# Max stretch between all-idle rounds. Bounds steady-state KV chatter of a
# P-process world to O(P^2)/cap reads per second against the coordination
# service; a fresh enqueue wakes the engine loop immediately (both
# engines), so the cap costs at most one peer's remaining backoff of
# first-op latency after an idle stretch, not per-op latency.
_IDLE_BACKOFF_CAP_S = float(os.environ.get(
    "HVD_NEGOTIATION_IDLE_MAX", "1.0"))

OPS = ("allreduce", "allgather", "broadcast")

# (namespace, key) pairs left behind by closed coordinators of earlier
# generations (final round keys + tombstones, ≤3 per generation). A
# lagging peer may still need them, so deletion is deferred until the next
# generation's first successful round proves every peer has moved on.
# Entries sharing the reclaimer's own namespace are skipped: production
# generations always get fresh namespaces (make_coordinator), so a
# same-namespace entry means an unrelated world (unit tests) — deleting
# would race its live rounds.
_residue: List[Tuple[str, str]] = []
_residue_lock = threading.Lock()


def negotiation_enabled() -> bool:
    """HVD_NEGOTIATION=0 disables the protocol (multi-controller runs then
    fall back to unfused, name-ordered execution)."""
    val = (os.environ.get("HVD_NEGOTIATION")
           or os.environ.get("HOROVOD_NEGOTIATION") or "1")
    return val.lower() not in ("0", "false", "off")


def negotiation_timeout_s() -> float:
    return float(os.environ.get("HVD_NEGOTIATION_TIMEOUT", "600"))


def aggregation_enabled() -> bool:
    """HVD_NEGOTIATION_AGGREGATE=1 routes each round through a process-0
    digest key: p0 reads the P-1 peer keys and republishes the combined
    tables once, every peer reads that ONE key — total KV load per
    round drops from P·(P-1) reads to 2·(P-1), the reference's
    gather-tree shape (rank-0 MPI_Gatherv tick + response broadcast,
    operations.cc:2117-2131). Must be set on EVERY process. Off by
    default: the symmetric protocol has no master to fail, and its
    round latency is fine at small P (measured curve: docs/running.md)."""
    val = (os.environ.get("HVD_NEGOTIATION_AGGREGATE")
           or os.environ.get("HOROVOD_NEGOTIATION_AGGREGATE") or "0")
    return val.lower() not in ("0", "false", "off")


def cache_capacity_from_env() -> int:
    """HVD_CACHE_CAPACITY: max cached tensor identities per process
    (0 disables the negotiation response cache). Must be set identically
    on every process — like the reference's HOROVOD_CACHE_CAPACITY
    (response_cache.cc), mixed settings are a misconfiguration the
    protocol fails fast on."""
    val = (os.environ.get("HVD_CACHE_CAPACITY")
           or os.environ.get("HOROVOD_CACHE_CAPACITY"))
    if not val:
        return 1024
    if val.lower() in ("false", "off", "no"):
        # The sibling boolean knobs (HVD_NEGOTIATION*) accept these
        # spellings for "disabled" — honor them here too rather than
        # silently enabling the cache at the default.
        return 0
    try:
        return max(0, int(val))
    except ValueError:
        LOG.warning("unparseable HVD_CACHE_CAPACITY=%r; using the "
                    "default 1024 (set 0/off to disable)", val)
        return 1024


# Current world epoch (elastic worlds bump it on every reconfiguration;
# static worlds stay at 0). Carried by KVTimeout messages so a timed-out
# wait names both the key and the world incarnation it waited in.
_world_epoch = 0


def set_world_epoch(epoch: int):
    global _world_epoch
    _world_epoch = int(epoch)


def world_epoch() -> int:
    return _world_epoch


# Elastic liveness probe (core/elastic.py registers it): maps a process
# index to its death-verdict reason, or None while it is presumed alive.
# Blocked negotiation reads consult it between poll slices so a dead peer
# fails the round within a heartbeat lease instead of the full
# negotiation timeout.
_liveness_probe = None


def set_liveness_probe(probe):
    global _liveness_probe
    _liveness_probe = probe


class KVTimeout(Exception):
    def __init__(self, key: str = "", epoch: Optional[int] = None):
        self.key = key
        self.epoch = _world_epoch if epoch is None else int(epoch)
        super().__init__(
            f"timed out waiting for KV key '{key}' "
            f"(world epoch {self.epoch})")


class KVError(Exception):
    pass


class PeerLost(KVError):
    """A blocked negotiation read aborted because the awaited peer has an
    elastic death verdict (missed-heartbeat KV lease) — fail over now
    instead of waiting out the negotiation timeout."""

    def __init__(self, process: int, reason: str):
        self.process = process
        super().__init__(
            f"process {process} declared dead by the elastic heartbeat "
            f"lease ({reason}); world epoch {_world_epoch} must "
            "reconfigure")


def _poll_slices(jitter: "random.Random"):
    """Yield blocked-read slice durations: jittered exponential backoff
    from _POLL_SLICE_MIN_S to _POLL_SLICE_MAX_S."""
    s = _POLL_SLICE_MIN_S
    while True:
        yield s * jitter.uniform(0.75, 1.25)
        s = min(s * 2.0, _POLL_SLICE_MAX_S)


class PeerShutdown(Exception):
    def __init__(self, process: int):
        super().__init__(f"process {process} shut down during negotiation")
        self.process = process


def is_shutdownish(exc: Exception) -> bool:
    """True when a negotiation failure means a CLEAN shutdown (peer
    tombstone or local teardown) rather than a fault. Both engines rate
    the same messages the same way — post-poison rounds re-raise
    KVError(dead) whose TEXT still names the original cause, so the
    check is by substring, and the flight recorder is only dumped for
    the non-clean cases."""
    msg = str(exc)
    return (isinstance(exc, PeerShutdown)
            or "shut down" in msg       # peer tombstone
            or "shutting down" in msg)  # local shutdown


class NegotiationTimeout(Exception):
    def __init__(self, process: int, waited_s: float):
        super().__init__(
            f"negotiation timed out after {waited_s:.0f}s waiting for "
            f"process {process}; it may have crashed or stopped its engine")
        self.process = process


class JaxKV:
    """KV backend over the JAX coordination service."""

    def __init__(self):
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise KVError("jax.distributed is not initialized")
        self._client = client

    def set(self, key: str, value: str):
        try:
            # Fault site kv.set (core/faultline.py): inside the wrap so
            # an injected error surfaces as KVError like an organic one;
            # 'torn' swaps in a half-written value.
            value = _flt.kv_set(key, value)
            self._client.key_value_set(key, value)
        except Exception as exc:
            raise KVError(str(exc)) from None

    def get(self, key: str, timeout_s: float) -> str:
        try:
            # Fault site kv.get: delay sleeps here (a slow KV read);
            # error surfaces as KVError like an organic RPC failure.
            _flt.kv_get(key)
        except _flt.FaultInjected as exc:
            raise KVError(str(exc)) from None
        fn = getattr(self._client, "blocking_key_value_get", None)
        if fn is None:
            # No server-side blocking get on this client: emulate with
            # try_get polls under jittered exponential backoff (a fixed
            # short-interval spin would hammer the KV store for the
            # whole wait).
            deadline = time.monotonic() + timeout_s
            slices = _poll_slices(random.Random())
            while True:
                val = self.try_get(key)
                if val is not None:
                    return val
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise KVTimeout(key)
                time.sleep(min(next(slices), remaining))
        try:
            return fn(key, max(1, int(timeout_s * 1000)))
        except Exception as exc:  # DEADLINE_EXCEEDED / connection errors
            msg = str(exc)
            if "DEADLINE_EXCEEDED" in msg or "deadline" in msg.lower():
                raise KVTimeout(key) from None
            raise KVError(msg) from None

    def try_get(self, key: str) -> Optional[str]:
        if _flt.kv_try_get(key):
            return None  # fault site kv.try_get: the key 'vanished'
        try:
            fn = getattr(self._client, "key_value_try_get", None)
            if fn is not None:
                return fn(key)
            # Newer jaxlib clients dropped key_value_try_get: emulate the
            # non-blocking probe with a near-zero-timeout blocking get
            # (an absent key surfaces as DEADLINE_EXCEEDED -> None).
            # Only probe paths use this (tombstone checks between poll
            # slices), so the extra 50 ms rides an already-blocked wait.
            return self._client.blocking_key_value_get(key, 50)
        except Exception:
            return None

    def delete(self, key: str):
        try:
            self._client.key_value_delete(key)
        except Exception:
            pass  # cleanup is best-effort


class LocalKV:
    """In-memory KV shared by instances created from the same ``store``
    dict — lets unit tests run N coordinators on N threads."""

    def __init__(self, store: dict, cond: Optional[threading.Condition] = None):
        self._store = store
        self._cond = cond or store.setdefault(
            "__cond__", threading.Condition())

    def set(self, key: str, value: str):
        # Same fault sites as JaxKV (core/faultline.py): the unit tier
        # exercises every KV injection mode on this backend, and an
        # injected error must surface as KVError on both.
        try:
            value = _flt.kv_set(key, value)
        except _flt.FaultInjected as exc:
            raise KVError(str(exc)) from None
        with self._cond:
            self._store[key] = value
            self._cond.notify_all()

    def get(self, key: str, timeout_s: float) -> str:
        try:
            _flt.kv_get(key)
        except _flt.FaultInjected as exc:
            raise KVError(str(exc)) from None
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while key not in self._store:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise KVTimeout(key)
                self._cond.wait(remaining)
            return self._store[key]

    def try_get(self, key: str) -> Optional[str]:
        if _flt.kv_try_get(key):
            return None
        with self._cond:
            return self._store.get(key)

    def delete(self, key: str):
        with self._cond:
            self._store.pop(key, None)


@dataclass(frozen=True)
class RequestMeta:
    """One pending collective's identity — the MPIRequest analogue
    (reference: common/mpi_message.h:44-95)."""

    name: str
    op: str
    dtype: str
    itemsize: int
    shape: Tuple[int, ...]
    average: bool = False
    root_rank: int = 0
    prescale: float = 1.0
    age_s: float = 0.0
    nbytes: int = 0
    # Engine wire policy ('none'/'int8'/'fp8' — core/engine.py). Part of
    # the cross-process fingerprint: a world where processes disagree on
    # a tensor's wire format would dequantize garbage, so mixed policies
    # fail fast BY NAME at negotiation (the HVD_CACHE_CAPACITY
    # precedent: misconfiguration surfaces on the first round).
    compression: str = "none"
    # Per-tier DCN wire policy of the hierarchical two-phase route
    # (mutually exclusive with `compression` — core/engine.py
    # check_wire_exclusive). Same cross-process fingerprint rule: a
    # world where processes disagree on which tier quantizes would
    # exchange mismatched payloads, so mixed per-tier policies fail
    # fast BY NAME at negotiation.
    compression_dcn: str = "none"
    # Priority class code (core/engine.py PRIORITY_CODES; lower drains
    # first). Part of the cross-process fingerprint: a world where
    # processes disagree on a tensor's class would compose different
    # fused batches and drain in different orders, so mixed priorities
    # fail fast BY NAME at negotiation (the HVD_COMPRESSION precedent).
    priority: int = 1

    def wire(self) -> list:
        return [self.name, self.op, self.dtype, self.itemsize,
                list(self.shape), int(self.average), self.root_rank,
                self.prescale, round(self.age_s, 3), self.nbytes,
                self.compression, self.compression_dcn, self.priority]

    @staticmethod
    def from_wire(w: list) -> "RequestMeta":
        return RequestMeta(name=w[0], op=w[1], dtype=w[2], itemsize=w[3],
                           shape=tuple(w[4]), average=bool(w[5]),
                           root_rank=w[6], prescale=w[7], age_s=w[8],
                           nbytes=w[9],
                           compression=w[10] if len(w) > 10 else "none",
                           compression_dcn=(w[11] if len(w) > 11
                                            else "none"),
                           priority=int(w[12]) if len(w) > 12 else 1)


@dataclass
class Group:
    """One decided execution unit: indices into the local entry list.
    ``error`` set => complete those entries with that error instead."""

    indices: List[int]
    error: Optional[str] = None


@dataclass
class Decision:
    groups: List[Group]
    cycle_time_s: Optional[float] = None
    fusion_threshold: Optional[int] = None
    idle_backoff_s: float = 0.0
    # True when this round took the response-cache bitvector fast path
    # (decide() skipped; groups from the memoized composition).
    cached: bool = False


class ResponseCache:
    """Per-process LRU of previously-agreed request identities, keyed by
    tensor name (reference: horovod/common/response_cache.cc).

    Every process mutates its cache ONLY from round data all processes
    observe identically (the agreed tables of full rounds, the decoded
    bit-union of fast rounds), so bit assignment, LRU order, evictions
    and the epoch advance in lockstep — equal epochs imply equal
    name↔bit structure on every process, which is what makes a peer's
    bitvector decodable locally. NOT thread-safe: owned by the
    coordinator, driven by the engine's dispatch thread."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        # name -> [bit, identity, wire_len]; insertion/touch order IS the
        # LRU order (dict preserves it; _touch re-appends).
        self._slots: Dict[str, list] = {}
        self._names: Dict[int, str] = {}  # bit -> name
        self._next_bit = 0
        # Evicted positions, reused smallest-first (a min-heap): the
        # bitvector mask stays bounded by the live-set high-water mark
        # instead of growing with cumulative distinct-name insertions
        # under churn (train/eval phase alternation). Reuse is safe:
        # frees and re-allocations happen only in lockstep full-round
        # maintenance, and every eviction advances the epoch.
        self._free_bits: List[int] = []
        self.epoch = 0
        self.evictions = 0  # cumulative, for telemetry/invalidations

    def __len__(self):
        return len(self._slots)

    @staticmethod
    def _identity(m: RequestMeta) -> tuple:
        """The full request identity a hit must match — stricter than
        ``_fingerprint`` (no allgather dim-0 wildcard: a per-step-varying
        first dim must renegotiate; everything except the submit-time
        ``age_s`` counts)."""
        return (m.op, m.dtype, m.itemsize, tuple(m.shape), m.average,
                m.root_rank, m.prescale, m.nbytes, m.compression,
                m.compression_dcn, m.priority)

    def lookup(self, m: RequestMeta) -> Optional[int]:
        """Bit of a cached identical request, or None (a changed shape/
        dtype/op under the same name is a miss, never a stale hit)."""
        slot = self._slots.get(m.name)
        if slot is None or slot[1] != self._identity(m):
            return None
        return slot[0]

    def bit_of(self, name: str) -> Optional[int]:
        slot = self._slots.get(name)
        return None if slot is None else slot[0]

    def meta_of(self, bit: int) -> Optional[RequestMeta]:
        name = self._names.get(bit)
        if name is None:
            return None
        ident = self._slots[name][1]
        (op, dtype, itemsize, shape, average, root, prescale, nbytes,
         compression, compression_dcn, priority) = ident
        return RequestMeta(name=name, op=op, dtype=dtype,
                           itemsize=itemsize, shape=shape, average=average,
                           root_rank=root, prescale=prescale,
                           nbytes=nbytes, compression=compression,
                           compression_dcn=compression_dcn,
                           priority=priority)

    def wire_len(self, bit: int) -> int:
        name = self._names.get(bit)
        return 0 if name is None else self._slots[name][2]

    def insert(self, m: RequestMeta):
        """Insert or update one agreed request. Callers drive this in a
        DETERMINISTIC order from round data every process shares."""
        slot = self._slots.get(m.name)
        ident = self._identity(m)
        wire_len = len(json.dumps(m.wire()))
        if slot is not None:
            slot[1] = ident  # same bit: the update is lockstep too
            slot[2] = wire_len
            self._touch(m.name)
            return
        if self._free_bits:
            bit = heapq.heappop(self._free_bits)
        else:
            bit = self._next_bit
            self._next_bit += 1
        self._slots[m.name] = [bit, ident, wire_len]
        self._names[bit] = m.name

    def _touch(self, name: str):
        self._slots[name] = self._slots.pop(name)

    def touch(self, names) -> None:
        """Refresh recency for every cached name in ``names`` —
        iterated sorted so LRU order stays identical everywhere."""
        for n in sorted(names):
            if n in self._slots:
                self._touch(n)

    def evict_over_capacity(self) -> int:
        """Drop LRU entries beyond capacity. Any eviction advances the
        epoch (a freed bit must never be misread by an in-flight
        assumption) — and means the evicted tensor's next submission
        misses, forcing a full-table round."""
        evicted = 0
        while len(self._slots) > self.capacity:
            name = next(iter(self._slots))
            bit = self._slots.pop(name)[0]
            del self._names[bit]
            heapq.heappush(self._free_bits, bit)
            evicted += 1
        if evicted:
            self.epoch += 1
            self.evictions += evicted
        return evicted

    def evict(self, name: str) -> bool:
        """Drop one entry (epoch advances). Normal operation never calls
        this asymmetrically — it exists for coherence tests and for a
        future invalidate-by-name surface."""
        slot = self._slots.pop(name, None)
        if slot is None:
            return False
        del self._names[slot[0]]
        heapq.heappush(self._free_bits, slot[0])
        self.epoch += 1
        self.evictions += 1
        return True

    def invalidate(self, epoch: Optional[int] = None):
        """Full clear + epoch advance (the lockstep divergence
        resolution: every process clears to the same fresh epoch)."""
        self._slots.clear()
        self._names.clear()
        self._next_bit = 0
        self._free_bits.clear()
        self.epoch = (self.epoch + 1) if epoch is None else int(epoch)

    # -- bitvector wire form -------------------------------------------------

    @staticmethod
    def encode(bits) -> str:
        """Set of bit positions -> compact hex mask (the wire form: the
        mask is bounded by the live-set high-water mark — evicted
        positions are reused — so a 1024-entry cache stays ~256 hex
        chars vs the full per-tensor wire tables)."""
        mask = 0
        for b in bits:
            mask |= 1 << b
        return format(mask, "x")

    @staticmethod
    def decode_mask(hexmask: str):
        mask = int(hexmask, 16)
        out = set()
        bit = 0
        while mask:
            if mask & 1:
                out.add(bit)
            mask >>= 1
            bit += 1
        return out


def _fingerprint(m: RequestMeta):
    """Identity that must agree across processes for one tensor name.
    Allgather legitimately permits differing first dims (reference:
    MPI_Allgatherv sizes, operations.cc:810-857)."""
    shape = m.shape[1:] if m.op == "allgather" else m.shape
    dim0 = ("*",) if m.op == "allgather" else ()
    return (m.op, m.dtype, m.itemsize, dim0 + tuple(shape), m.average,
            m.root_rank, m.prescale, m.compression, m.compression_dcn,
            m.priority)


def _mismatch_message(name: str, metas: Dict[int, RequestMeta]) -> str:
    """Reference-style coordinator error (operations.cc:315-517 builds
    'Mismatched ...' ERROR responses)."""
    pids = sorted(metas)
    a = metas[pids[0]]
    for pid in pids[1:]:
        b = metas[pid]
        if _fingerprint(b) == _fingerprint(a):
            continue  # this process agrees with pids[0]; find the one that doesn't
        if a.op != b.op:
            field, va, vb = "collective operations", a.op, b.op
        elif a.dtype != b.dtype or a.itemsize != b.itemsize:
            field, va, vb = "data types", a.dtype, b.dtype
        elif a.root_rank != b.root_rank:
            field, va, vb = "root ranks", a.root_rank, b.root_rank
        elif a.compression != b.compression:
            # Mixed wire policies would dequantize garbage — the
            # misconfiguration fails fast by name, like the
            # HVD_CACHE_CAPACITY capacity handshake.
            field, va, vb = ("wire compression policies (set "
                             "HVD_COMPRESSION / the Compression policy "
                             "identically on every process)",
                             a.compression, b.compression)
        elif a.compression_dcn != b.compression_dcn:
            # Mixed per-tier policies: one side would quantize the
            # cross-tier shard, the other would not — same fail-fast
            # contract as the uniform wire policy above.
            field, va, vb = ("DCN-tier wire policies (set "
                             "HVD_COMPRESSION_DCN / compression_dcn "
                             "identically on every process)",
                             a.compression_dcn, b.compression_dcn)
        elif a.priority != b.priority:
            # Mixed priority classes would compose different fused
            # batches and drain in different orders across the world —
            # same fail-fast contract as the wire policies above.
            field, va, vb = ("priority classes (set HVD_PRIORITY / the "
                             "per-request priority identically on every "
                             "process)", a.priority, b.priority)
        elif a.average != b.average or a.prescale != b.prescale:
            field, va, vb = ("reduction options",
                             (a.average, a.prescale), (b.average, b.prescale))
        else:
            field, va, vb = "tensor shapes", list(a.shape), list(b.shape)
        return (f"Mismatched {field} for collective '{name}': process "
                f"{pids[0]} submitted {va}, process {pid} submitted {vb}. "
                "All processes must submit identical collectives for the "
                "same tensor name.")
    return f"Mismatched collective '{name}'"


def _fuse_names(ready: Sequence[RequestMeta],
                fusion_threshold: int) -> List[List[str]]:
    """Group ready requests for execution: (priority, name) order —
    lower class codes drain first, lexicographic names within a class —
    with allreduces fused per (priority, dtype, average, prescale) up
    to the threshold, so fused batches stay priority-uniform. Pure +
    deterministic — shared by ``decide`` (full rounds) and the
    response-cache fast path (which memoizes the result). Deadline
    margin is deliberately NOT in this shared key: it is clock-local
    and would diverge across processes."""
    name_groups: List[List[str]] = []
    open_groups: Dict[tuple, List[str]] = {}
    open_bytes: Dict[tuple, int] = {}
    for m in sorted(ready, key=lambda m: (m.priority, m.name)):
        if m.op != "allreduce" or fusion_threshold <= 0:
            name_groups.append([m.name])
            continue
        key = (m.priority, m.dtype, m.average, m.prescale, m.compression,
               m.compression_dcn)
        g = open_groups.get(key)
        if g is not None and open_bytes[key] + m.nbytes <= fusion_threshold:
            g.append(m.name)
            open_bytes[key] += m.nbytes
        else:
            g = [m.name]
            open_groups[key] = g
            open_bytes[key] = m.nbytes
            name_groups.append(g)
    return name_groups


def decide(tables: Dict[int, List[RequestMeta]], my_entries: Sequence[RequestMeta],
           fusion_threshold: int) -> List[Group]:
    """The pure decision function — MUST be deterministic in its inputs,
    since every process computes it independently on identical inputs
    (the role of rank 0 + MPI_Bcast in the reference)."""
    by_name: Dict[str, Dict[int, RequestMeta]] = {}
    for pid, metas in tables.items():
        for m in metas:
            by_name.setdefault(m.name, {})[pid] = m
    nproc = len(tables)
    local_index = {m.name: i for i, m in enumerate(my_entries)}

    ready, errors = [], {}
    for name in sorted(by_name):
        metas = by_name[name]
        if len(metas) < nproc or name not in local_index:
            continue  # not announced everywhere yet — stays pending
        fps = {_fingerprint(m) for m in metas.values()}
        if len(fps) > 1:
            errors[name] = _mismatch_message(name, metas)
        else:
            ready.append(metas[0] if 0 in metas else next(iter(metas.values())))

    groups = [Group([local_index[n] for n in names])
              for names in _fuse_names(ready, fusion_threshold)]
    for name in sorted(errors):
        groups.append(Group([local_index[name]], errors[name]))
    return groups


class Coordinator:
    """Per-engine negotiation endpoint. NOT thread-safe: exactly one
    thread (the engine's dispatch loop) drives ``negotiate``."""

    def __init__(self, kv, num_processes: int, process_index: int,
                 cycle_time_s: float, fusion_threshold: int,
                 stall_warning_s: float = 60.0,
                 timeout_s: Optional[float] = None,
                 namespace: str = "hvd/neg/g0",
                 cache_capacity: Optional[int] = None):
        self.kv = kv
        self.nproc = num_processes
        self.pid = process_index
        self.cycle_time_s = cycle_time_s
        self.fusion_threshold = fusion_threshold
        self.stall_warning_s = stall_warning_s
        self.timeout_s = (negotiation_timeout_s()
                          if timeout_s is None else timeout_s)
        self.ns = namespace
        self.round = 0
        self.dead: Optional[str] = None  # poisoned: message to fail with
        self.idle_rounds = 0
        self.waiting_on: Optional[int] = None  # peer a blocked read awaits
        self.last_tables: Dict[int, set] = {}
        self._last_stall_warn = 0.0
        self._closed = False
        # Poll-slice jitter stream (blocked reads): seeded per process so
        # probe trains de-synchronize across the world.
        self._jitter = random.Random((process_index + 1) * 7919)
        # Control-plane cost accounting (docs/running.md "negotiation
        # cost"): rounds completed, wall time inside negotiate(), and
        # actual KV get attempts (each blocking poll slice counts — the
        # O(P) reads/round that make total KV load O(P^2)/round).
        self.stats = {"rounds": 0, "round_s": 0.0, "kv_gets": 0,
                      "fast_rounds": 0}
        self.aggregate = aggregation_enabled()
        # Negotiation response cache (the bitvector fast path). Off under
        # the gather-tree round shape: aggregation already collapses the
        # per-round KV load to O(P) through p0's digest, and the digest
        # republish would carry the full tables regardless.
        if cache_capacity is None:
            cache_capacity = cache_capacity_from_env()
        self.cache = (ResponseCache(cache_capacity)
                      if cache_capacity > 0 and not self.aggregate else None)
        # (frozenset of ready bits, fusion) -> agreed [[name, ...], ...]:
        # the memoized group composition a fast round reuses so decide()
        # is skipped entirely. Valid only between cache mutations — full
        # rounds clear it.
        self._group_memo: Dict[tuple, List[List[str]]] = {}
        self._cache_bytes_saved = 0
        # Consumed round keys are reclaimed up to (excluding) this round.
        self._gc_round = 0
        # Straggler attribution state: first-observed announce time per
        # (name, process) from the round tables, and the names already
        # charged to the telemetry tracker (a recurring name — per-step
        # gradients — is forgotten once it leaves every table, so the
        # next instance is charged afresh).
        self._announce: Dict[str, Dict[int, float]] = {}
        self._blamed: set = set()
        # Clock-anchor exchange (distributed tracing): once ready,
        # clock_offset_us is rank 0's wall↔monotonic bridge — the common
        # time base every per-rank timeline embeds — and clock_rtt_us is
        # the measured KV round trip bounding the estimate's error
        # (Cristian-style; on one host the shared CLOCK_MONOTONIC makes
        # the bridge exact). The exchange is non-blocking: it retries at
        # round granularity until rank 0's anchor appears.
        self.clock_offset_us = 0
        self.clock_rtt_us: Optional[int] = None
        self.clock_ready = False
        self._clock_attempts = 0
        self._clock_published = False
        self._clock_anchor: Optional[Tuple[float, float]] = None

    # -- keys ---------------------------------------------------------------

    def _round_key(self, rnd: int, pid: int) -> str:
        return f"{self.ns}/r{rnd}/p{pid}"

    def _digest_key(self, rnd: int) -> str:
        return f"{self.ns}/r{rnd}/all"

    def _tomb_key(self, pid: int) -> str:
        return f"{self.ns}/dead/p{pid}"

    def _clock_key(self, pid: int) -> str:
        return f"{self.ns}/clock/p{pid}"

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        """Publish the shutdown tombstone (peers blocked on our next round
        key discover it between poll slices).

        Keys this generation leaves behind — the final round key(s) and
        the tombstone — cannot be deleted here: a lagging peer may still
        need them to finish its round or fail fast. They are recorded as
        residue and reclaimed by the NEXT generation's first successful
        round (every peer publishing the new generation's round 0 proves
        the old generation is fully consumed everywhere). Only the last
        generation's ≤3 keys outlive the job's final engine."""
        if self._closed:
            return
        self._closed = True
        with _residue_lock:
            _residue.append((self.ns, self._tomb_key(self.pid)))
            _residue.append((self.ns, self._clock_key(self.pid)))
            _residue.append((self.ns, self._round_key(self.round, self.pid)))
            if self.round > 0:
                _residue.append(
                    (self.ns, self._round_key(self.round - 1, self.pid)))
            if self.aggregate and self.pid == 0:
                _residue.append((self.ns, self._digest_key(self.round)))
                if self.round > 0:
                    _residue.append(
                        (self.ns, self._digest_key(self.round - 1)))
        try:
            self.kv.set(self._tomb_key(self.pid), str(self.round))
        except Exception:
            pass  # coordination service may already be down at exit

    # -- clock-anchor exchange (distributed tracing) ------------------------

    def _maybe_clock_sync(self):
        """Exchange monotonic-clock anchors so per-rank timelines merge on
        a common base (Cristian-style through the KV store). Each process
        publishes ``(wall, monotonic)`` captured at one instant — a
        timeless mapping between its two clocks — and adopts rank 0's
        wall↔monotonic bridge as the common-base offset. The residual
        error is the inter-host wall-clock skew plus the measured KV
        round trip recorded as the bound; same-host processes share
        CLOCK_MONOTONIC, making the bridge exact. Non-blocking: retried
        once per round until rank 0's anchor appears, then never again."""
        if self.clock_ready or self._clock_attempts >= 16:
            return
        self._clock_attempts += 1
        try:
            if not self._clock_published:
                self._clock_anchor = (time.time(), time.monotonic())
                self.kv.set(self._clock_key(self.pid),
                            json.dumps(list(self._clock_anchor)))
                self._clock_published = True
            if self.pid == 0:
                wall0, mono0 = self._clock_anchor
            else:
                raw = self.kv.try_get(self._clock_key(0))
                if raw is None:
                    return  # rank 0 not up yet — retry next round
                wall0, mono0 = json.loads(raw)
            # The measured KV round trip (the error bound): ONE blocking
            # read of a key we just proved exists — our own anchor.
            # Runs exactly once, on the attempt that completes the sync,
            # with a sub-second cap: a degraded KV store must not stack
            # multi-second probes onto the negotiation round path for a
            # telemetry-only bound (the bound is then simply absent).
            t0 = time.monotonic()
            self.kv.get(self._clock_key(self.pid), 0.9)
            rtt_us = int((time.monotonic() - t0) * 1e6)
            self.clock_offset_us = int((wall0 - mono0) * 1e6)
            self.clock_rtt_us = rtt_us
            self.clock_ready = True
        except (KVTimeout, KVError, ValueError, TypeError):
            pass  # purely additive — never fail a round over clock sync

    # -- the round ----------------------------------------------------------

    def _read_peer(self, rnd: int, peer: int, digest: bool = False,
                   deadline: Optional[float] = None) -> dict:
        key = self._digest_key(rnd) if digest else self._round_key(rnd, peer)
        if deadline is None:
            deadline = time.monotonic() + self.timeout_s
            if digest:
                # Digest readers outlast p0's own (whole-gather) deadline
                # so p0's error digest — which carries the TRUE straggler
                # attribution — arrives before this reader gives up and
                # can only blame p0. p0's deadline starts at p0's OWN
                # round entry, which may lag this reader's by up to a full
                # timeout while a third peer stalls p0's gather (r4
                # advisor), hence a whole extra timeout_s of grace, not
                # just poll slack; a DEAD p0 is still caught within one
                # poll slice by the tombstone check below. The slack is
                # two MAX slices: backed-off polls detect p0's own
                # deadline with up to one max-slice granularity before
                # it can republish the error digest.
                deadline += self.timeout_s + 2 * _POLL_SLICE_MAX_S
        self.waiting_on = peer
        slices = _poll_slices(self._jitter)
        try:
            while True:
                if self._closed:
                    # Local shutdown while blocked on a silent peer (e.g.
                    # it was SIGKILLed without a tombstone): abort the
                    # round so engine teardown is not held hostage for the
                    # full negotiation timeout.
                    raise KVError("local engine is shutting down")
                if _liveness_probe is not None:
                    verdict = _liveness_probe(peer)
                    if verdict is not None:
                        # Elastic death verdict: the peer will never
                        # publish — fail the round NOW with the
                        # attribution instead of waiting out timeout_s.
                        raise PeerLost(peer, verdict)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise NegotiationTimeout(peer, self.timeout_s)
                try:
                    self.stats["kv_gets"] += 1
                    raw = self.kv.get(key, min(next(slices), remaining))
                    msg = json.loads(raw)
                    if digest and "error" in msg:
                        # p0's gather failed; it republished the real
                        # cause so every peer fails with the true
                        # attribution instead of blaming p0.
                        raise KVError(
                            f"negotiation round failed: {msg['error']}")
                    return msg
                except KVTimeout:
                    if self.kv.try_get(self._tomb_key(peer)) is not None:
                        raise PeerShutdown(peer) from None
                    # Mixed-mode fail-fast (r4 advisor): a world where
                    # HVD_NEGOTIATION_AGGREGATE differs across processes
                    # deadlocks silently — each side waits on a key the
                    # other mode never writes. The OTHER mode's key
                    # appearing while ours does not is the signature;
                    # surface the misconfiguration instead of hanging.
                    other = (self._round_key(rnd, 0) if digest
                             else (self._digest_key(rnd) if peer == 0
                                   else None))
                    if other and self.kv.try_get(other) is not None:
                        raise KVError(
                            "HVD_NEGOTIATION_AGGREGATE mismatch: process "
                            f"0 is running {'symmetric' if digest else 'gather-tree'} "
                            "rounds while this process expects "
                            f"{'gather-tree' if digest else 'symmetric'} — "
                            "set HVD_NEGOTIATION_AGGREGATE identically on "
                            "every process") from None
        finally:
            self.waiting_on = None

    def negotiate(self, entries: Sequence[RequestMeta]) -> Decision:
        """Run one round. Raises PeerShutdown / NegotiationTimeout /
        KVError — callers fail their pending entries and poison the
        engine's negotiated path."""
        if self.dead:
            raise KVError(self.dead)
        self._maybe_clock_sync()
        t_round = time.monotonic()
        rnd = self.round
        cache = self.cache
        my_bits: Optional[set] = None
        if cache is not None:
            bits = [cache.lookup(m) for m in entries]
            nhits = sum(b is not None for b in bits)
            if nhits:
                _tele.REGISTRY.counter(
                    "engine.negotiation.cache_hits").inc(nhits)
            if len(bits) - nhits:
                _tele.REGISTRY.counter(
                    "engine.negotiation.cache_misses").inc(len(bits) - nhits)
            if nhits == len(bits):
                # Every local entry hit (vacuously true when idle): this
                # process's half of the round is one compact bitvector +
                # the cache epoch instead of the full wire tables.
                my_bits = set(bits)
        if my_bits is not None:
            msg = {"bits": ResponseCache.encode(my_bits), "ce": cache.epoch,
                   "cc": cache.capacity}
        else:
            msg = {"entries": [m.wire() for m in entries]}
            if cache is not None:
                msg["ce"] = cache.epoch
                msg["cc"] = cache.capacity
        if self.pid == 0:
            msg["params"] = [self.cycle_time_s, self.fusion_threshold]
        payload = json.dumps(msg)
        if not (self.aggregate and self.pid == 0):
            # In gather-tree mode p0's table rides the digest only —
            # publishing its per-round key too would be a dead KV write
            # on exactly the plane aggregation exists to unload.
            try:
                self.kv.set(self._round_key(rnd, self.pid), payload)
            except KVError as exc:
                self.dead = str(exc)
                self.close()  # tombstone: let peers fail fast, not time out
                raise
        if my_bits is not None:
            # Wire bytes NOT published: the full table this process would
            # have sent, minus the bitvector it did send.
            full_len = (sum(cache.wire_len(b) for b in my_bits)
                        + 2 * len(my_bits) + 16)
            self._cache_bytes_saved += max(0, full_len - len(payload))

        tables: Dict[int, List[RequestMeta]] = {
            self.pid: list(entries)}
        # Processes whose round message was a decodable bitvector (self
        # included when publishing one) — the round is a FAST round when
        # this covers the whole world.
        bit_tables: Dict[int, set] = {}
        if my_bits is not None:
            bit_tables[self.pid] = my_bits
        epochs_seen = {cache.epoch} if cache is not None else set()
        epoch_mismatch = False
        params = msg.get("params")
        try:
            if self.aggregate and self.pid != 0:
                # Gather-tree mode, non-root: ONE read — p0's digest of
                # the whole round. Stall attribution still works (the
                # digest carries every table); if p0's gather fails it
                # republishes the true cause as an error digest (below),
                # which this read surfaces verbatim.
                digest = self._read_peer(rnd, 0, digest=True)
                tables = {int(p): [RequestMeta.from_wire(w) for w in ws]
                          for p, ws in digest["tables"].items()}
                params = digest.get("params")
            else:
                # p0's gather shares ONE deadline across all peers (the
                # symmetric path's per-peer deadline would let p0 outlast
                # every digest reader by up to (P-1)x, leaving them only
                # p0 to blame on timeout).
                gather_deadline = (time.monotonic() + self.timeout_s
                                   if self.aggregate else None)
                for peer in range(self.nproc):
                    if peer == self.pid:
                        continue
                    peer_msg = self._read_peer(rnd, peer,
                                               deadline=gather_deadline)
                    if peer == 0:
                        params = peer_msg.get("params")
                    # Capacity handshake: every cache-carrying message
                    # names its capacity, so ANY mix — zero vs nonzero,
                    # or two different nonzero values (whose lone-rank
                    # evictions would otherwise oscillate the world
                    # through endless epoch resets) — fails fast by
                    # name on the very first round.
                    peer_cc = peer_msg.get("cc")
                    my_cc = None if cache is None else cache.capacity
                    if peer_cc != my_cc:
                        raise KVError(
                            "HVD_CACHE_CAPACITY mismatch: process "
                            f"{peer} runs response-cache capacity "
                            f"{peer_cc or 0} while this process runs "
                            f"{my_cc or 0} — set HVD_CACHE_CAPACITY "
                            "identically on every process")
                    if "bits" in peer_msg:
                        epochs_seen.add(peer_msg.get("ce"))
                        if peer_msg.get("ce") != cache.epoch:
                            # Divergent cache state (e.g. a peer evicted
                            # on its own): resolved in lockstep below.
                            epoch_mismatch = True
                            continue
                        pbits = ResponseCache.decode_mask(peer_msg["bits"])
                        metas = [cache.meta_of(b) for b in sorted(pbits)]
                        if any(m is None for m in metas):
                            # Equal epochs imply identical name<->bit
                            # structure; an unknown slot is a protocol
                            # invariant violation — surface it, never
                            # guess a table.
                            raise KVError(
                                "negotiation response cache corrupt: "
                                f"process {peer} referenced an unknown "
                                f"cache slot at epoch {cache.epoch}")
                        bit_tables[peer] = pbits
                        tables[peer] = metas
                        full_len = (sum(cache.wire_len(b) for b in pbits)
                                    + 2 * len(pbits) + 16)
                        self._cache_bytes_saved += max(
                            0, full_len - len(json.dumps(peer_msg)))
                    else:
                        ce = peer_msg.get("ce")
                        if ce is not None:
                            epochs_seen.add(ce)
                            if cache is not None and ce != cache.epoch:
                                epoch_mismatch = True
                        tables[peer] = [RequestMeta.from_wire(w)
                                        for w in peer_msg.get("entries", [])]
                if self.aggregate:
                    # Gather-tree mode, root: republish the round once.
                    self.kv.set(self._digest_key(rnd), json.dumps({
                        "tables": {p: [m.wire() for m in ms]
                                   for p, ms in tables.items()},
                        "params": params}))
        except (PeerShutdown, NegotiationTimeout, KVError) as exc:
            self.dead = str(exc)
            if self.aggregate and self.pid == 0:
                # Blocked digest readers can only see p0: hand them the
                # REAL cause (e.g. which process timed out) before the
                # tombstone makes them fail generically.
                try:
                    self.kv.set(self._digest_key(rnd),
                                json.dumps({"error": str(exc)}))
                except Exception:
                    pass
            # We will never publish another round: tombstone so peers
            # blocked on OUR next message fail fast instead of waiting
            # out the full negotiation timeout.
            self.close()
            raise
        if cache is not None:
            _tele.REGISTRY.gauge(
                "engine.negotiation.cache_bytes_saved").set(
                    self._cache_bytes_saved)
        self.round = rnd + 1
        # Everyone has published round `rnd`, so every round `< rnd` is
        # fully consumed — reclaim all of ours that are still out there,
        # so a long training's store stays bounded (satellite: KV GC).
        while self._gc_round < rnd:
            self.kv.delete(self._round_key(self._gc_round, self.pid))
            if self.aggregate and self.pid == 0:
                self.kv.delete(self._digest_key(self._gc_round))
            self._gc_round += 1
        if rnd == 0:
            # Every peer is in THIS generation now, so no one can ever
            # read a prior generation's keys again — reclaim the residue
            # its close() recorded (round keys + tombstones).
            with _residue_lock:
                stale = [k for ns, k in _residue if ns != self.ns]
                # Same-namespace entries stay queued for a future
                # different-namespace generation to reclaim.
                _residue[:] = [e for e in _residue if e[0] == self.ns]
            for key in stale:
                self.kv.delete(key)

        cycle_s, fusion = (params if params else
                           (self.cycle_time_s, self.fusion_threshold))
        if self.pid != 0:
            self.cycle_time_s, self.fusion_threshold = cycle_s, int(fusion)
        # Process 0's OWN attributes are the source of truth (the
        # autotuner / set_params writes them): adopting the round's echo
        # here would stomp a value set mid-round and lose it forever.
        # The DECISION still uses the round's published params on every
        # process — batch composition must be computed from identical
        # inputs everywhere; a newer local value joins the next round.

        if epoch_mismatch:
            # Lockstep coherence reset: every process read the SAME
            # message set, so every process observes the mismatch,
            # schedules NOTHING this round (entries stay pending — a
            # stale hit is structurally impossible), clears its cache to
            # the same fresh epoch, and resynchronizes on the next
            # full-table round.
            cache.invalidate(max(e for e in epochs_seen
                                 if e is not None) + 1)
            self._group_memo.clear()
            _tele.REGISTRY.counter(
                "engine.negotiation.cache_invalidations").inc()
            LOG.warning(
                "negotiation response cache epoch diverged across "
                "processes; caches cleared in lockstep (epoch %d), "
                "renegotiating with full tables", cache.epoch)
            self.stats["rounds"] += 1
            self.stats["round_s"] += time.monotonic() - t_round
            return Decision(groups=[], cycle_time_s=cycle_s,
                            fusion_threshold=int(fusion))

        fast = (my_bits is not None and len(bit_tables) == self.nproc)
        if fast:
            # Every process's round was an equal-epoch bitvector: the
            # identities are pinned by the cache agreement, so readiness
            # is pure set intersection and decide() is skipped for the
            # memoized composition.
            ready_bits = set(my_bits)
            for s in bit_tables.values():
                ready_bits &= s
            groups = self._fast_groups(entries, ready_bits, int(fusion))
            announced = set()
            for metas in tables.values():
                announced.update(m.name for m in metas)
            cache.touch(announced)  # recency from common knowledge
            self.stats["fast_rounds"] += 1
        else:
            groups = decide(tables, entries, int(fusion))
            if cache is not None:
                self._cache_maintain(tables, groups, entries)
        self.last_tables = {pid: {m.name for m in metas}
                            for pid, metas in tables.items()}
        self._track_stragglers()
        total = sum(len(t) for t in tables.values())
        self.idle_rounds = self.idle_rounds + 1 if total == 0 else 0
        backoff = 0.0
        if self.idle_rounds:
            backoff = min(cycle_s * (2 ** min(self.idle_rounds, 10)),
                          _IDLE_BACKOFF_CAP_S)
        self._maybe_warn_stalls(entries)
        self.stats["rounds"] += 1
        self.stats["round_s"] += time.monotonic() - t_round
        return Decision(groups=groups, cycle_time_s=cycle_s,
                        fusion_threshold=int(fusion),
                        idle_backoff_s=backoff, cached=fast)

    # -- response-cache internals -------------------------------------------

    def _fast_groups(self, entries: Sequence[RequestMeta], ready_bits,
                     fusion: int) -> List[Group]:
        """Group composition of a fast round without decide(): the ready
        set is the bit intersection, the composition is memoized per
        (ready set, fusion threshold) — same agreed grouping on every
        process because the cached identities are identical."""
        key = (frozenset(ready_bits), int(fusion))
        name_groups = self._group_memo.get(key)
        if name_groups is None:
            cache = self.cache
            ready = [m for m in entries
                     if cache.bit_of(m.name) in ready_bits]
            name_groups = _fuse_names(ready, int(fusion))
            if len(self._group_memo) > 256:
                self._group_memo.clear()  # bounded memory
            self._group_memo[key] = name_groups
        local_index = {m.name: i for i, m in enumerate(entries)}
        return [Group([local_index[n] for n in g]) for g in name_groups]

    def _cache_maintain(self, tables: Dict[int, List[RequestMeta]],
                        groups: List[Group],
                        entries: Sequence[RequestMeta]):
        """Lockstep cache update after a full round. Inputs — the agreed
        tables and the decision computed from them — are identical on
        every process, and every mutation below iterates them in sorted
        order, so insertions, bit assignment, recency and LRU evictions
        advance identically everywhere (the induction that makes equal
        epochs imply identical caches)."""
        cache = self.cache
        announced = set()
        for metas in tables.values():
            announced.update(m.name for m in metas)
        cache.touch(announced)
        agreed = [i for g in groups if g.error is None for i in g.indices]
        for i in sorted(agreed, key=lambda i: entries[i].name):
            cache.insert(entries[i])
        evicted = cache.evict_over_capacity()
        if evicted:
            # Evicted identities will miss on their next submission —
            # the eviction-driven full-round fallback.
            _tele.REGISTRY.counter(
                "engine.negotiation.cache_invalidations").inc(evicted)
        self._group_memo.clear()  # composition may reference new state

    # -- stall attribution (reference: CheckForStalledTensors,
    # operations.cc:1535-1581 — names the ranks holding up each tensor) ----

    def _track_stragglers(self):
        """Distill per-process lateness from the round tables into the
        telemetry straggler tracker. Rounds tick even when this process
        is idle, so announce times are observed at round granularity
        (~cycle time) on EVERY process — a delayed peer is charged its
        lateness on the waiting processes and on itself alike."""
        now = time.monotonic()
        live = set()
        for pid, names in self.last_tables.items():
            for n in names:
                live.add(n)
                self._announce.setdefault(n, {}).setdefault(pid, now)
        for n in [n for n in self._announce if n not in live]:
            # Instance completed everywhere: forget, so a re-submission
            # of the same name (per-step gradients) is charged afresh.
            del self._announce[n]
            self._blamed.discard(n)
        for n, times in self._announce.items():
            if n not in self._blamed and len(times) >= self.nproc:
                self._blamed.add(n)
                _tele.STRAGGLERS.observe(n, times)

    def missing_processes(self, name: str) -> List[int]:
        if not self.last_tables:
            return []
        return [p for p in range(self.nproc)
                if name not in self.last_tables.get(p, set())]

    def counter_divergence_peer(
            self, name: str) -> Optional[Tuple[int, str]]:
        """A peer holding a HIGHER-numbered name of the same family while
        this stalled (lower) name is missing from it proves the stall
        cannot resolve: names are constructed in program order, so a peer
        that reached the higher number either announced the lower one too
        (then it would not be missing) or never will — its counters
        diverged (asymmetric tf.function retrace / rank-conditional
        program) or its sequential executor wedged on a different
        blocking single-op collective. A peer holding only LOWER numbers
        is an ordinary straggler and gets no hint."""
        skeleton = re.sub(r"\d+", "#", name)
        mine = tuple(int(d) for d in re.findall(r"\d+", name))
        for p in range(self.nproc):
            names = self.last_tables.get(p, set())
            if name in names:
                continue
            for other in names:
                if other == name or re.sub(r"\d+", "#", other) != skeleton:
                    continue
                theirs = tuple(int(d) for d in re.findall(r"\d+", other))
                if theirs > mine:
                    return p, other
        return None

    def _maybe_warn_stalls(self, entries: Sequence[RequestMeta]):
        if self.stall_warning_s <= 0:
            return
        now = time.monotonic()
        if now - self._last_stall_warn < self.stall_warning_s:
            return
        lines = []
        for m in entries:
            if m.age_s <= self.stall_warning_s:
                continue
            missing = self.missing_processes(m.name)
            if missing:
                line = (f"{m.name} [missing from process(es): "
                        f"{', '.join(map(str, missing))}]")
                hint = divergence_hint(self, m.name)
                if hint:
                    line += hint
                lines.append(line)
        if lines:
            self._last_stall_warn = now
            worst = _tele.STRAGGLERS.worst_line()
            if worst:
                lines.append(worst)
            LOG.warning(
                "One or more tensors were submitted to be reduced, gathered "
                "or broadcast by a subset of processes and are waiting for "
                "the remainder for more than %ds: %s",
                int(self.stall_warning_s), "; ".join(lines))


def divergence_hint(coordinator, name: str) -> Optional[str]:
    """Human-readable diagnosis when a stalled tensor's peers hold a
    same-family, different-numbered name (see counter_divergence_peer) —
    shared by the coordinator's warn path and the engines' watchdogs so
    every stall report carries the same fail-fast hint."""
    diverged = coordinator.counter_divergence_peer(name)
    if not diverged:
        return None
    p, other = diverged
    return (f" [process {p} holds '{other}' — same collective family, "
            "different sequence number: either op-construction order "
            "diverged across processes (asymmetric tf.function retrace / "
            "rank-conditional program — every process must build "
            "identical programs) or independent blocking single-op "
            "collectives wedged under a sequential executor (submit them "
            "as ONE group instead)]")


# Engine generation counter: each engine shutdown/re-init cycle gets a
# fresh KV namespace, so a new incarnation never consumes the previous
# one's tombstone or final-round keys. Engine lifecycle must be COLLECTIVE
# across processes (every process inits/shuts down the same number of
# times) — the same contract MPI_Init/Finalize imposes on the reference.
_generation = 0


def make_coordinator(cycle_time_s: float, fusion_threshold: int,
                     stall_warning_s: float,
                     warn_stalls: bool = True,
                     cache_capacity: Optional[int] = None
                     ) -> Optional[Coordinator]:
    """Build a Coordinator for the current topology, or None when the run
    is single-controller / negotiation is disabled / no KV service."""
    global _generation

    from horovod_tpu.common import topology as topo

    if not (topo.is_initialized() and topo.num_processes() > 1):
        return None
    if not negotiation_enabled():
        return None
    try:
        kv = JaxKV()
    except KVError:
        LOG.warning("multi-controller run without a jax.distributed "
                    "coordination service; negotiation disabled (fusion "
                    "and the response cache stay off)")
        return None
    gen = _generation
    _generation += 1
    return Coordinator(kv, topo.num_processes(), topo.process_index(),
                       cycle_time_s, fusion_threshold,
                       stall_warning_s if warn_stalls else 0.0,
                       namespace=f"hvd/neg/g{gen}",
                       cache_capacity=cache_capacity)
