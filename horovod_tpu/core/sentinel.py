"""Performance sentinel: in-loop anomaly watchdog + auto-capture profiling.

The observability stack before this module was *passive*: the telemetry
registry (core/telemetry.py) counts, the timelines (core/timeline.py)
record, the profiler (utils/profiler.py) captures — but only when a human
asks. This module watches the run while it trains (reference rationale:
Horovod's timeline made scaling problems *diagnosable*, arxiv 1802.05799
§5; the MLPerf TPU-pod work shows sustained-throughput claims only hold
when measurement is continuous, arxiv 1909.09756 §3):

- **Watchdog** (:class:`StepWatchdog`): a rolling step-time baseline
  (EWMA + p99 over the same observations the telemetry dispatch/step
  rings hold) per *origin* — the keras Trainer's wall step time, the
  ``hvd.jax.jit`` wrapper's dispatch latency. A step exceeding the
  anomaly threshold fires ONCE (cooldown, no re-trigger storm): flight
  recorder dump, a bounded profiler capture of the next few steps, and
  an attributed verdict — recompile (jax compile events fired during
  the step) vs straggler rank (the telemetry straggler report gained
  imposed wait) vs engine stall (both engines' stall paths call
  :func:`note_stall`) vs HBM-traffic jump (the post-anomaly capture's
  measured bytes/step vs the previous capture).
- **Auto-capture** (:class:`AutoCapture`): with ``HVD_PROFILE_DIR`` set,
  ``HVD_PROFILE_EVERY=N`` takes a periodic capture of
  ``HVD_PROFILE_STEPS`` steps every N steps, and SIGUSR2 takes one on
  demand. Each capture folds through
  :func:`horovod_tpu.utils.xplane.hbm_json` into measured
  hbm_gb_per_step / membw_util (and MFU when
  :func:`set_flops_per_step` was told the program's cost) and appends
  one JSON record to ``$HVD_PROFILE_DIR/perf.jsonl`` — the health log
  ``utils/perfwatch`` gates against.
- **Health** (:func:`health`): the ``/healthz`` payload the
  ``HVD_TELEMETRY_PORT`` endpoint serves (core/telemetry_http.py) —
  watchdog verdicts + last-step age.

The bench.py AOT hot window stays uninstrumented: the sentinel only sees
the per-call dispatch boundary (``_InstrumentedJit``) and post-window
captures — never the inside of the compiled program.

Knobs (all env): ``HVD_WATCHDOG`` (default on; 0 disables),
``HVD_WATCHDOG_FACTOR`` (default 3.0 × EWMA), ``HVD_WATCHDOG_P99_MULT``
(default 2.0 × p99 — the threshold is the max of both),
``HVD_WATCHDOG_MIN_STEPS`` (warmup, default 32),
``HVD_WATCHDOG_COOLDOWN`` (steps between firings per origin, default
200), ``HVD_PROFILE_DIR``, ``HVD_PROFILE_EVERY``, ``HVD_PROFILE_STEPS``
(default 3). Stdlib-only on the observe path; jax/xplane are imported
only when a capture actually starts/folds.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from horovod_tpu.core import telemetry as tele
from horovod_tpu.core import timeline as tl

LOG = logging.getLogger("horovod_tpu.sentinel")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# Recompile detection: jax monitoring events
# ---------------------------------------------------------------------------

_compile_lock = threading.Lock()
_compile_count = 0
_compile_listener_installed = False


def _on_compile_event(name: str, *args, **kwargs):
    global _compile_count
    if "backend_compile" in name:
        with _compile_lock:
            _compile_count += 1
        tele.REGISTRY.counter("jax.compiles").inc()


def install_compile_listener():
    """Count XLA compiles through jax's monitoring events (best-effort:
    the listener API is semi-public — a jax without it just means the
    'recompile' verdict is never produced). Idempotent."""
    global _compile_listener_installed
    with _compile_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True
    try:
        import jax.monitoring as _mon

        _mon.register_event_duration_secs_listener(_on_compile_event)
    except Exception:  # pragma: no cover - jax drift
        pass


def compile_count() -> int:
    with _compile_lock:
        return _compile_count


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------


class StepWatchdog:
    """Rolling step-time baseline for one origin (trainer / dispatch).

    ``observe`` returns an anomaly dict when the step exceeds the
    threshold — ``max(factor × EWMA, p99_mult × p99)`` — after
    ``min_steps`` of warmup. The FIRED sample is not folded into the
    baseline (one outlier must not drag the EWMA up and mask the next);
    a fired anomaly then opens a ``cooldown``-step window in which
    further excursions are counted as ``suppressed`` but do not re-fire
    — and those samples DO fold in, so a persistent regime shift
    becomes the new baseline (one dump per shift) instead of a dump
    storm when the cooldown expires."""

    def __init__(self, origin: str, factor: float = 3.0,
                 p99_mult: float = 2.0, min_steps: int = 32,
                 cooldown: int = 200, window: int = 256,
                 alpha: float = 0.1):
        self.origin = origin
        self.factor = factor
        self.p99_mult = p99_mult
        self.min_steps = max(2, min_steps)
        self.cooldown = max(1, cooldown)
        self.alpha = alpha
        self.ewma: Optional[float] = None
        self.steps = 0
        self.anomalies = 0
        self.suppressed = 0
        self._window: deque = deque(maxlen=window)
        self._cooldown_left = 0
        self._lock = threading.Lock()
        # p99 is refreshed every _P99_REFRESH inserts, not per observe:
        # sorting 256 samples on every dispatch would dominate the
        # claimed ~1-2 µs per-call overhead. A sixteen-step-stale p99
        # only delays threshold ADAPTATION, never detection (the EWMA
        # half of the threshold is always current).
        self._p99_cache: Optional[float] = None
        self._since_p99 = 0
        # Attribution context captured at the END of the previous step:
        # a delta over the anomalous step is evidence about THAT step.
        self._prev_compiles = compile_count()
        self._prev_strag_us = 0

    _P99_REFRESH = 16

    def _p99_locked(self) -> Optional[float]:
        if not self._window:
            return None
        w = sorted(self._window)
        return w[min(len(w) - 1, int(0.99 * (len(w) - 1) + 0.999))]

    def p99(self) -> Optional[float]:
        with self._lock:
            return self._p99_locked()

    def threshold(self) -> Optional[float]:
        """Current anomaly threshold in seconds, or None during warmup.
        Uses the cached p99 (refreshed every ``_P99_REFRESH`` inserts)."""
        if self.steps < self.min_steps or self.ewma is None:
            return None
        thr = self.factor * self.ewma
        if self._p99_cache is not None:
            thr = max(thr, self.p99_mult * self._p99_cache)
        return thr

    def _strag_total_us(self) -> int:
        try:
            return tele.STRAGGLERS.total_wait_us()
        except Exception:
            return 0

    def observe(self, step_s: float,
                allow_fire: bool = True) -> Optional[dict]:
        """Record one step; returns the anomaly context dict when this
        step fired (caller attributes/dumps), else None.
        ``allow_fire=False`` records an over-threshold sample as
        suppressed (the sentinel passes it when ANOTHER origin just
        fired on the same excursion — one slow compiled step must not
        dump twice through the trainer AND dispatch watchdogs)."""
        thr = self.threshold()
        anomalous = thr is not None and step_s > thr
        fired = None
        with self._lock:
            self.steps += 1
            if self._cooldown_left > 0 or (anomalous and not allow_fire):
                if self._cooldown_left > 0:
                    self._cooldown_left -= 1
                if anomalous:
                    self.suppressed += 1
                anomalous = False  # suppressed — baseline still protected
            elif anomalous:
                self.anomalies += 1
                self._cooldown_left = self.cooldown
                fired = {
                    "origin": self.origin,
                    "step_s": step_s,
                    "ewma_s": self.ewma,
                    "threshold_s": thr,
                }
            if fired is None:
                # Baseline update excludes the fired outlier.
                self._window.append(step_s)
                self.ewma = (step_s if self.ewma is None
                             else (1 - self.alpha) * self.ewma
                             + self.alpha * step_s)
                self._since_p99 += 1
                if (self._p99_cache is None
                        or self._since_p99 >= self._P99_REFRESH):
                    self._since_p99 = 0
                    self._p99_cache = self._p99_locked()
        # Attribution deltas over THIS step (read outside the lock; the
        # counters are process-global and monotonic).
        comp = compile_count()
        strag = self._strag_total_us()
        if fired is not None:
            fired["p99_s"] = self.p99()
            fired["compiles"] = comp - self._prev_compiles
            fired["straggler_wait_us"] = strag - self._prev_strag_us
        self._prev_compiles = comp
        self._prev_strag_us = strag
        return fired

    def summary(self) -> dict:
        p99 = self.p99()
        thr = self.threshold()
        return {
            "steps": self.steps,
            "ewma_ms": round(self.ewma * 1e3, 3) if self.ewma else None,
            "p99_ms": round(p99 * 1e3, 3) if p99 else None,
            "threshold_ms": round(thr * 1e3, 3) if thr else None,
            "anomalies": self.anomalies,
            "suppressed": self.suppressed,
        }


# ---------------------------------------------------------------------------
# Auto-capture
# ---------------------------------------------------------------------------

_SIGUSR2_INSTALLED = False
_SIGUSR2_PREV = None


def _on_sigusr2(signum, frame):
    """Module-level handler: looks up the CURRENT sentinel at signal
    time (a closure over one AutoCapture would pin a replaced sentinel
    forever and arm an orphan nobody steps). Signal-safe: one attribute
    write, no allocation, no locks."""
    s = _sentinel
    if s is not None:
        # Through request(), not a raw _pending write: its guard keeps
        # an armed watchdog capture from being displaced (compare +
        # attribute writes — still signal-safe).
        s.capture.request("sigusr2")
    if callable(_SIGUSR2_PREV):
        try:
            _SIGUSR2_PREV(signum, frame)
        except Exception:
            pass


def _install_sigusr2_once():
    global _SIGUSR2_INSTALLED, _SIGUSR2_PREV
    if _SIGUSR2_INSTALLED:
        return
    try:
        _SIGUSR2_PREV = signal.signal(signal.SIGUSR2, _on_sigusr2)
        _SIGUSR2_INSTALLED = True
    except (ValueError, AttributeError, OSError):
        pass  # non-main thread, or a platform without SIGUSR2


class AutoCapture:
    """Bounded XLA-profiler captures of the live training loop.

    Periodic (``HVD_PROFILE_EVERY`` steps, needs ``HVD_PROFILE_DIR``),
    on-demand (SIGUSR2, or :meth:`request`), and watchdog-triggered.
    Each capture spans the next ``HVD_PROFILE_STEPS`` observed steps,
    then folds asynchronously (the xplane parse imports tensorflow —
    never paid inside the training loop) into one ``perf.jsonl``
    record."""

    def __init__(self, sentinel: "Sentinel"):
        self._sentinel = sentinel
        self.dir = os.environ.get("HVD_PROFILE_DIR") or None
        self.every = _env_int("HVD_PROFILE_EVERY", 0)
        self.steps_per_capture = max(1, _env_int("HVD_PROFILE_STEPS", 3))
        self._seq = 0
        self._step = 0
        # ONE attribute holds (kind, verdict): the SIGUSR2 handler and
        # the training thread race on this slot, and two separate
        # fields could interleave into a sigusr2 kind carrying a
        # clobbered watchdog verdict.
        self._pending_req: Optional[tuple] = None
        self._active: Optional[dict] = None
        self._lock = threading.Lock()
        self.last_record: Optional[dict] = None
        self._last_hbm_gb: Optional[float] = None
        if self.dir:
            _install_sigusr2_once()

    # -- triggers ------------------------------------------------------------

    def request(self, kind: str, verdict: Optional[dict] = None):
        """Arm a capture starting at the next observed step (signal-safe:
        attribute compare + writes). An armed WATCHDOG request is never
        displaced by a lesser trigger — a SIGUSR2 landing right after an
        anomaly (the operator reacting to the warning) must not leave
        the verdict's capture pending forever. ``verdict`` rides along
        on watchdog requests so the fold resolves THE verdict that armed
        the capture, not whatever ``last_verdict`` holds by then."""
        req = self._pending_req
        if req is not None and req[0] == "watchdog" and kind != "watchdog":
            return
        self._pending_req = (kind, verdict)  # single atomic store

    # -- the per-step state machine ------------------------------------------

    def observe_step(self, step_s: float):
        with self._lock:
            if self._active is not None:
                self._active["step_times"].append(step_s)
                if len(self._active["step_times"]) >= \
                        self._active["steps"]:
                    self._stop_locked()
                return
            self._step += 1
            req, self._pending_req = self._pending_req, None
            kind, verdict = req if req is not None else (None, None)
            if kind is None and self.dir and self.every > 0 \
                    and self._step % self.every == 0:
                kind = "periodic"
            if kind is not None:
                self._start_locked(kind, verdict)

    def _start_locked(self, kind: str, verdict: Optional[dict] = None):
        base = self.dir
        if base is None:
            # Watchdog-triggered capture with no HVD_PROFILE_DIR: the
            # evidence still gets captured, into a kept tempdir named in
            # the verdict (no perf.jsonl without a configured home).
            base = tempfile.mkdtemp(prefix="hvd_sentinel_")
        self._seq += 1
        capdir = os.path.join(base, f"capture_{self._seq:04d}_{kind}")
        try:
            import jax

            os.makedirs(capdir, exist_ok=True)
            jax.profiler.start_trace(capdir)
        except Exception as exc:
            # Another trace active (bench --profile, a user's tensorboard
            # capture) or no jax: skip, never break the training loop —
            # but RESOLVE a pending watchdog verdict (its deferred
            # counter and /healthz "pending" marker must not dangle on
            # a capture that never happened).
            LOG.debug("sentinel capture skipped: %s", exc)
            self._sentinel._note_capture(
                {"capture_dir": None, "kind": kind,
                 "error": f"capture failed to start: {exc}"}, None,
                verdict=verdict)
            return
        self._active = {"kind": kind, "dir": capdir,
                        "steps": self.steps_per_capture,
                        "t0": time.time(), "step_times": [],
                        "verdict": verdict}
        tele.REGISTRY.counter("sentinel.captures.started").inc()

    def _stop_locked(self):
        active, self._active = self._active, None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            LOG.debug("sentinel capture stop failed: %s", exc)
            active["error"] = f"stop_trace failed: {exc}"
        threading.Thread(target=self._fold, args=(active,),
                         name="hvd-sentinel-fold", daemon=True).start()

    # -- folding (background thread) -----------------------------------------

    def _fold(self, active: dict):
        record = {
            "ts": round(time.time(), 3),
            "rank": tl._process_index(),
            "kind": active["kind"],
            "steps": len(active["step_times"]),
            "capture_dir": active["dir"],
            "step_time_ms": None,
            "hbm_gb_per_step": None,
            "hbm_gb_by_dtype": None,
            "membw_util": None,
            "mfu": None,
            "gflops_per_step": None,
            # Latest host-visible training loss (Trainer epoch
            # boundaries): the perfwatch trend table's convergence
            # column. None when no loop reported one.
            "final_loss": self._sentinel.last_loss,
            "error": active.get("error"),
        }
        times = active["step_times"]
        step_s = sum(times) / len(times) if times else None
        if step_s:
            record["step_time_ms"] = round(step_s * 1e3, 3)
        try:
            from horovod_tpu.utils import profiler

            files = profiler.trace_files(active["dir"])
            if not files:
                raise profiler.CaptureError(
                    f"capture produced no *.xplane.pb under "
                    f"{active['dir']}")
            from horovod_tpu.utils import xplane

            data = xplane.hbm_json(active["dir"],
                                   steps=max(1, len(times)))
            hbm_bytes = data["true_hbm_bytes_per_step"]
            record["hbm_gb_per_step"] = round(hbm_bytes / 1e9, 3)
            by_dtype = data.get("bytes_by_dtype_per_step") or None
            if by_dtype:
                # bf16-vs-f32 byte split (HBM diet round 2): schedule-
                # derived, so it audits the state_dtype policy — f32
                # bytes creeping back show up per capture in perf.jsonl.
                record["hbm_gb_by_dtype"] = {
                    dt: round(b / 1e9, 3) for dt, b in by_dtype.items()}
            import jax

            from horovod_tpu.utils import hardware as hw

            dev = jax.devices()[0]
            peak_bw = hw.peak_hbm_bw(dev)
            peak = hw.peak_flops(dev)
            if step_s and peak_bw and hbm_bytes:
                record["membw_util"] = round(
                    hbm_bytes / step_s / peak_bw, 3)
            flops = self._sentinel.flops_per_step
            if flops:
                record["gflops_per_step"] = round(flops / 1e9, 1)
                if step_s and peak:
                    record["mfu"] = round(flops / step_s / peak, 4)
        except Exception as exc:
            if record["error"] is None:
                record["error"] = str(exc).splitlines()[0][:300]
        self.last_record = record
        tele.REGISTRY.counter("sentinel.captures.folded").inc()
        if self.dir:
            try:
                with open(os.path.join(self.dir, "perf.jsonl"), "a") as fh:
                    fh.write(json.dumps(record) + "\n")
            except OSError as exc:
                LOG.warning("cannot append perf.jsonl: %s", exc)
        # HBM-jump attribution: a watchdog capture's traffic vs the last
        # known-good capture. Update BEFORE publishing the baseline.
        self._sentinel._note_capture(record, self._last_hbm_gb,
                                     verdict=active.get("verdict"))
        if record["hbm_gb_per_step"] is not None \
                and record["kind"] != "watchdog":
            self._last_hbm_gb = record["hbm_gb_per_step"]

    def summary(self) -> dict:
        return {
            "dir": self.dir,
            "every": self.every,
            "captures": self._seq,
            "active": self._active is not None,
            "last": self.last_record,
        }


# ---------------------------------------------------------------------------
# Sentinel (process singleton)
# ---------------------------------------------------------------------------


class Sentinel:
    """Per-process sentinel: per-origin watchdogs + one AutoCapture."""

    #: HBM-traffic jump factor for the post-anomaly capture verdict.
    HBM_JUMP = 1.10

    def __init__(self):
        self.enabled = os.environ.get("HVD_WATCHDOG", "1") not in (
            "0", "false", "off")
        self.factor = _env_float("HVD_WATCHDOG_FACTOR", 3.0)
        self.p99_mult = _env_float("HVD_WATCHDOG_P99_MULT", 2.0)
        self.min_steps = _env_int("HVD_WATCHDOG_MIN_STEPS", 32)
        self.cooldown = _env_int("HVD_WATCHDOG_COOLDOWN", 200)
        self.capture_on_anomaly = os.environ.get(
            "HVD_WATCHDOG_CAPTURE",
            "1" if os.environ.get("HVD_PROFILE_DIR") else "0") not in (
            "0", "false", "off")
        self.flops_per_step: Optional[float] = None
        self.watchdogs: Dict[str, StepWatchdog] = {}
        self.capture = AutoCapture(self)
        self.last_verdict: Optional[dict] = None
        self.last_step_wall: Optional[float] = None
        self.last_stall: Optional[dict] = None
        self.last_loss: Optional[float] = None
        self._lock = threading.Lock()
        # One real training step can be observed through SEVERAL origins
        # (the keras Trainer's wall time wraps a jitted call that itself
        # reports its dispatch): exactly one origin — "trainer" when one
        # exists, else the first seen — drives the capture state machine,
        # and a fresh firing suppresses other origins' firings on the
        # same excursion for a short wall window.
        self._capture_origin: Optional[str] = None
        self._last_fire_wall: Optional[float] = None
        if self.enabled:
            install_compile_listener()

    #: Wall seconds after a firing during which OTHER origins' anomalies
    #: are suppressed (the same slow step seen through two lenses).
    FIRE_SUPPRESS_S = 5.0

    # -- wiring --------------------------------------------------------------

    def watchdog(self, origin: str) -> StepWatchdog:
        with self._lock:
            wd = self.watchdogs.get(origin)
            if wd is None:
                wd = self.watchdogs[origin] = StepWatchdog(
                    origin, factor=self.factor, p99_mult=self.p99_mult,
                    min_steps=self.min_steps, cooldown=self.cooldown)
            return wd

    def observe_step(self, step_s: float, origin: str = "step"
                     ) -> Optional[dict]:
        """One observed step/dispatch. Cheap when nothing is armed: a
        deque append + a few compares. Returns the verdict when this
        step fired the watchdog."""
        now = time.time()
        self.last_step_wall = now
        # Capture stepping follows ONE origin ("trainer" preferred —
        # wall step time — else the first seen): a Trainer step would
        # otherwise be counted twice (its own observation + the wrapped
        # jit dispatch), halving the periodic cadence and folding
        # mixed-meaning step times into perf.jsonl.
        if self._capture_origin is None or origin == "trainer":
            self._capture_origin = origin
        if origin == self._capture_origin:
            self.capture.observe_step(step_s)
        if not self.enabled:
            return None
        allow = (self._last_fire_wall is None
                 or now - self._last_fire_wall > self.FIRE_SUPPRESS_S)
        fired = self.watchdog(origin).observe(step_s, allow_fire=allow)
        if fired is None:
            return None
        self._last_fire_wall = now
        return self._fire(fired)

    def note_stall(self, reason: str, rank: Optional[int] = None):
        """Both engines' stall paths land here: the stall becomes health
        state and attribution context for the next anomaly verdict."""
        self.last_stall = {"wall": time.time(),
                           "reason": str(reason).splitlines()[0][:300],
                           "rank": rank}
        tele.REGISTRY.counter("sentinel.stalls").inc()

    def note_loss(self, loss):
        """Latest host-visible training loss (the Trainer reports it at
        epoch boundaries, where it is already a host float): auto-capture
        perf.jsonl records carry it as ``final_loss`` so the perfwatch
        trend table can show convergence next to throughput."""
        try:
            self.last_loss = float(loss)
        except (TypeError, ValueError):
            pass

    def note_numerics(self, kind: str, info: dict) -> dict:
        """A numerics verdict (``nonfinite`` / ``diverged`` — see
        core/numerics.py): same dump + health machinery as the watchdog
        verdicts, independent of ``HVD_WATCHDOG`` (a disabled step
        watchdog must not silence numerics events). The flight dump
        rides the existing rate-limit (``HVD_FLIGHT_MIN_INTERVAL``) and
        retention cap; ``last_verdict`` recency degrades ``/healthz`` to
        warn/503 exactly like a watchdog firing."""
        verdict = {"origin": info.get("origin", "numerics"),
                   "verdict": kind,
                   "wall_us": int(time.time() * 1e6)}
        verdict.update({k: v for k, v in info.items() if k != "origin"})
        tele.REGISTRY.counter(f"sentinel.verdict.{kind}").inc()
        events = self._flight_events()
        last_ts = events[-1].get("ts") if events else None
        events.append({"name": "NUMERICS_VERDICT", "ph": "i",
                       "ts": (int(last_ts) + 1
                              if isinstance(last_ts, (int, float))
                              else 0),
                       "args": {k: v for k, v in verdict.items()
                                if k != "dump"}})
        detail = (f"tensor {info['tensor']!r}" if info.get("tensor")
                  else f"step {info.get('step')}")
        who = info.get("ranks") or info.get("processes")
        verdict["dump"] = tl.dump_and_warn(
            events,
            f"numerics: {kind} at {detail}"
            + (f", bucket(s) {sorted(info['buckets'])}"
               if info.get("buckets") else "")
            + (f", rank(s)/process(es) {who}" if who else ""),
            None, LOG)
        self.last_verdict = verdict
        return verdict

    def note_hang(self, verdict: dict,
                  rank: Optional[int] = None) -> dict:
        """The hang doctor's attributed verdict (core/doctor.py) lands
        here as verdict kind ``hang``: counted under the existing
        ``sentinel.verdict.*`` vocabulary, recorded as ``last_verdict``
        (recency degrades ``/healthz`` to warn/503 exactly like a
        watchdog or numerics verdict). No flight dump of its own — the
        hang-class dump that triggered the diagnosis already embeds the
        doctor verdict, and a second dump here would only burn the rate
        limit."""
        v = {"origin": "doctor", "verdict": "hang",
             "wall_us": int(time.time() * 1e6)}
        if rank is not None:
            v["rank"] = rank
        v.update({k: val for k, val in verdict.items()
                  if k not in ("origin", "verdict", "wall_us")})
        tele.REGISTRY.counter("sentinel.verdict.hang").inc()
        self.last_verdict = v
        return v

    def set_flops_per_step(self, flops: Optional[float]):
        """Tell the sentinel the compiled step's FLOP cost so capture
        records can carry MFU (the training loop knows it from XLA cost
        analysis; the sentinel cannot derive it from a trace)."""
        self.flops_per_step = float(flops) if flops else None

    # -- anomaly pipeline ----------------------------------------------------

    def _fire(self, fired: dict) -> dict:
        verdict = dict(fired)
        verdict["wall_us"] = int(time.time() * 1e6)
        # Attribution priority: a recompile explains the whole excursion;
        # a straggler explains a collective-bound one; a fresh engine
        # stall explains a host-path one; otherwise the capture may still
        # attribute HBM traffic after it folds. The straggler delta must
        # be COMMENSURATE with the excursion (≥25% of step − baseline):
        # multi-process rounds accrue a few µs of skew every step, and
        # blaming a peer for an unrelated slow step would pre-empt the
        # stall/HBM attributions with an innocent name.
        excursion_us = max(
            0.0, fired["step_s"] - (fired.get("ewma_s") or 0.0)) * 1e6
        if fired.get("compiles"):
            verdict["verdict"] = "recompile"
        elif fired.get("straggler_wait_us", 0) > 0.25 * excursion_us:
            worst = tele.STRAGGLERS.worst()
            verdict["verdict"] = "straggler"
            if worst is not None:
                verdict["straggler"] = {"process": worst[0],
                                        "wait_us": worst[1]}
        elif self.last_stall and (time.time() - self.last_stall["wall"]
                                  < 10 * max(fired["step_s"], 1.0)):
            verdict["verdict"] = "engine_stall"
            verdict["stall"] = self.last_stall["reason"]
        else:
            verdict["verdict"] = "unattributed"
        tele.REGISTRY.counter("sentinel.anomalies").inc()
        # An "unattributed" verdict with a capture pending may still be
        # upgraded to "hbm_traffic" when the capture folds — defer its
        # per-verdict counter to _note_capture so the counters sum to
        # sentinel.anomalies instead of double-counting upgrades.
        defer_counter = (verdict["verdict"] == "unattributed"
                         and self.capture_on_anomaly)
        if not defer_counter:
            tele.REGISTRY.counter(
                f"sentinel.verdict.{verdict['verdict']}").inc()
        # Flight dump: engine ring if an engine is live, plus the verdict
        # itself as the trailing event (post-mortem readers see the
        # attribution next to the events that led to it).
        events = self._flight_events()
        # The verdict event must share the ring events' (timeline-
        # relative) clock, or ts-sorted readers (trace merge accepts
        # dump files) place it eons away from the events it explains.
        last_ts = events[-1].get("ts") if events else None
        events.append({"name": "WATCHDOG_VERDICT", "ph": "i",
                       "ts": (int(last_ts) + 1
                              if isinstance(last_ts, (int, float))
                              else 0),
                       "args": {k: v for k, v in verdict.items()
                                if k != "dump"}})
        verdict["dump"] = tl.dump_and_warn(
            events,
            f"watchdog: {verdict['origin']} step "
            f"{fired['step_s'] * 1e3:.1f} ms exceeded threshold "
            f"{fired['threshold_s'] * 1e3:.1f} ms "
            f"({verdict['verdict']})",
            None, LOG)
        # Bounded capture of the next few steps (opt-in by default only
        # when HVD_PROFILE_DIR is configured: an unsolicited
        # start_trace would collide with user captures).
        verdict["capture"] = None
        if self.capture_on_anomaly:
            verdict["capture"] = "pending"
            self.capture.request("watchdog", verdict)
        self.last_verdict = verdict
        return verdict

    def _flight_events(self) -> List[dict]:
        """The live engine's flight-recorder ring, when one exists (the
        compiled path has no engine — its dump carries telemetry + the
        verdict only)."""
        try:
            from horovod_tpu.core import engine as _eng

            e = _eng._engine
            if e is None:
                return []
            if hasattr(e, "recent_events"):  # native
                return list(e.recent_events())
            return list(e.timeline.recent())
        except Exception:
            return []

    def _note_capture(self, record: dict, prev_hbm_gb: Optional[float],
                      verdict: Optional[dict] = None):
        """Capture folded: finalize a pending HBM-jump attribution (and
        land the per-verdict counter _fire deferred). Only a WATCHDOG
        capture resolves a pending verdict — a periodic capture that was
        already running when the anomaly fired folds first and carries
        PRE-anomaly traffic; the armed watchdog request stays pending in
        AutoCapture and resolves the verdict when its own capture folds.
        ``verdict`` is the object that ARMED the capture (rode through
        AutoCapture) — never ``last_verdict``, which a second anomaly
        may have replaced by fold time."""
        v = verdict
        if record.get("kind") != "watchdog":
            return
        if v is not None and v.get("capture") == "pending":
            v["capture"] = record["capture_dir"]
            cur = record.get("hbm_gb_per_step")
            if (v.get("verdict") == "unattributed" and cur
                    and prev_hbm_gb
                    and cur > prev_hbm_gb * self.HBM_JUMP):
                v["verdict"] = "hbm_traffic"
                v["hbm_gb_per_step"] = cur
                v["hbm_gb_per_step_baseline"] = prev_hbm_gb
            if v.get("verdict") in ("unattributed", "hbm_traffic"):
                tele.REGISTRY.counter(
                    f"sentinel.verdict.{v['verdict']}").inc()

    # -- health --------------------------------------------------------------

    def health(self) -> dict:
        """The ``/healthz`` payload: watchdog verdict + last-step age.

        Degrades to ``warn`` (HTTP 503) on a recent verdict/stall AND on
        a **stale** loop — no observed step for longer than
        ``max(HVD_HEALTH_STALE_S (60), 20 × the largest origin EWMA)``.
        A rank hung inside a compiled-path collective stops calling
        observe_step entirely; without the staleness arm the endpoint
        would serve 200 forever through the one failure mode it most
        exists to catch. (A run that legitimately left its training
        loop — eval, checkpointing — also reads warn until steps
        resume: the endpoint measures training liveness.)"""
        draining = _draining_reason()
        now = time.time()
        age = (round(now - self.last_step_wall, 3)
               if self.last_step_wall else None)
        recent_verdict = (self.last_verdict is not None
                          and now - self.last_verdict["wall_us"] / 1e6
                          < 300)
        recent_stall = (self.last_stall is not None
                        and now - self.last_stall["wall"] < 300)
        stale_after = _env_float("HVD_HEALTH_STALE_S", 60.0)
        with self._lock:
            # Snapshot under the lock: the HTTP thread serves health()
            # while the training thread may be registering a new origin.
            wds = sorted(self.watchdogs.items())
            ewmas = [w.ewma for _, w in wds if w.ewma]
        if ewmas:
            stale_after = max(stale_after, 20.0 * max(ewmas))
        stale = age is not None and age > stale_after
        # Verdict recency is checked BEFORE the no-step-yet "init" arm:
        # a numerics verdict can fire from the engine path before any
        # training step is observed (core/numerics.py), and /healthz
        # must degrade on it regardless.
        # Serving-plane admission state (core/engine.py
        # admission_summary — covers both engines via the singleton):
        # queue depth, per-class in-flight vs budget, saturation.
        admission = None
        try:
            from horovod_tpu.core import engine as _eng

            admission = _eng.admission_summary()
        except Exception:  # pragma: no cover - defensive
            pass
        if draining is not None:
            # Deliberate drain (engine quiesce / graceful preemption):
            # load balancers must stop routing here NOW — the endpoint
            # serves non-200 for it (telemetry_http treats everything
            # outside ok/init as 503), and the payload says why.
            status = "draining"
        elif admission is not None and admission.get("saturated"):
            # Overload: at least one priority class is at its admission
            # budget RIGHT NOW — new submits in that class are being
            # rejected. Non-200 so load balancers route serving traffic
            # elsewhere until in-flight work drains below the budget.
            status = "saturated"
        elif recent_verdict or recent_stall:
            status = "warn"
        elif age is None:
            status = "init"
        elif stale:
            status = "warn"
        else:
            status = "ok"
        try:
            from horovod_tpu.core import numerics as _num

            numerics = _num.summary()
        except Exception:  # pragma: no cover - defensive
            numerics = None
        # Elastic world state (core/elastic.py): a shrunk world is a
        # DEGRADED deployment even when every surviving step is healthy
        # — /healthz must say so until the mesh regrows.
        world = None
        try:
            from horovod_tpu.core import elastic as _elastic

            world = _elastic.world_summary()
        except Exception:  # pragma: no cover - defensive
            pass
        if world is not None and world.get("degraded") \
                and status in ("ok", "init"):
            status = "warn"
        return {
            "status": status,
            "draining": draining,
            "admission": admission,
            "world": world,
            "rank": tl._process_index(),
            "pid": os.getpid(),
            "enabled": self.enabled,
            "last_step_age_s": age,
            "stale": stale,
            "stale_after_s": round(stale_after, 1),
            "watchdogs": {o: w.summary() for o, w in wds},
            "verdict": self.last_verdict,
            "stall": self.last_stall,
            "numerics": numerics,
            "capture": self.capture.summary(),
        }


_sentinel: Optional[Sentinel] = None
_sentinel_lock = threading.Lock()


def get_sentinel() -> Sentinel:
    global _sentinel
    with _sentinel_lock:
        if _sentinel is None:
            _sentinel = Sentinel()
        return _sentinel


def reset_sentinel():
    """Drop the singleton (tests only — the replacement re-reads env)."""
    global _sentinel
    with _sentinel_lock:
        _sentinel = None


def observe_step(step_s: float, origin: str = "step") -> Optional[dict]:
    """Module-level hook the Trainer / jit wrapper call per step. Never
    raises: the sentinel must not take the training loop down."""
    try:
        return get_sentinel().observe_step(step_s, origin)
    except Exception:  # pragma: no cover - defensive
        return None


def note_stall(reason: str, rank: Optional[int] = None):
    """Module-level hook the engines' stall paths call. Never raises."""
    try:
        get_sentinel().note_stall(reason, rank)
    except Exception:  # pragma: no cover - defensive
        pass


# Deliberate-drain marker (engine quiesce / graceful preemption): module
# state, not Sentinel state — a drain survives a sentinel reset and must
# be visible before any sentinel was ever built.
_draining: Optional[str] = None
_draining_lock = threading.Lock()


def note_draining(reason: Optional[str]):
    """Mark this process as draining (``/healthz`` answers ``draining``
    with a non-200 status until cleared with None). The engines' quiesce
    and the graceful-preemption ladder call it. Never raises."""
    global _draining
    with _draining_lock:
        _draining = str(reason) if reason is not None else None


def _draining_reason() -> Optional[str]:
    with _draining_lock:
        return _draining


def note_loss(loss):
    """Module-level hook the Trainer calls with the latest host-visible
    loss (epoch boundaries). Never raises."""
    try:
        get_sentinel().note_loss(loss)
    except Exception:  # pragma: no cover - defensive
        pass


def note_numerics(kind: str, info: dict) -> dict:
    """Module-level hook the numerics observatory calls. Unlike the
    other module hooks this RETURNS the verdict (the caller attributes
    and may raise under the halt policy) but still never raises
    itself."""
    try:
        return get_sentinel().note_numerics(kind, info)
    except Exception:  # pragma: no cover - defensive
        return {"verdict": kind, "dump": None}


def note_hang(verdict: dict, rank: Optional[int] = None):
    """Module-level hook the hang doctor calls with its attributed
    verdict. Never raises."""
    try:
        return get_sentinel().note_hang(verdict, rank)
    except Exception:  # pragma: no cover - defensive
        return None


def health() -> dict:
    return get_sentinel().health()


def set_flops_per_step(flops: Optional[float]):
    get_sentinel().set_flops_per_step(flops)
