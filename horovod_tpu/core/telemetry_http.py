"""Per-rank localhost telemetry endpoint (``HVD_TELEMETRY_PORT``).

The file exporter (``HVD_TELEMETRY_FILE``) is pull-by-filesystem; this
is pull-by-HTTP — the shape every metrics stack already scrapes. One
daemon thread per process serves, on ``127.0.0.1`` only (observability
must not open the host to the network):

- ``GET /metrics`` — the registry's Prometheus text exposition (exactly
  the bytes ``HVD_TELEMETRY_FILE`` would hold, same parser in
  ``utils/stats``);
- ``GET /healthz`` — the sentinel's health JSON (watchdog verdicts +
  last-step age, ``core/sentinel.py``), HTTP 200 when ``ok``/``init``,
  503 when ``warn`` (load balancers and ``curl -f`` get the right
  signal for free);
- ``GET /fleet`` — the merged world rollup (``core/fleet.py``): per-op
  latency quantiles, per-rank heatmap with STALE/DEAD marking, world
  gauges. Degrades to a one-rank rollup off rank 0 / with the plane
  down. Rank 0's ``/metrics`` also carries the per-rank-labeled
  ``hvd_fleet_*`` series when the plane is up;
- ``GET /doctor`` — an on-demand hang diagnosis (``core/doctor.py``):
  this rank publishes its per-entry inspect table and diffs it against
  every visible peer snapshot, answering with the attributed verdict —
  the remote spelling of ``hvd.diagnose()``.

Activation mirrors the file exporter: lazy, on the first telemetry
touch, only when ``HVD_TELEMETRY_PORT`` is set and nonzero. The
launcher's ``--telemetry-port-base B`` gives child ``i`` port ``B+i``.
A busy port logs one warning and stays off — a second process on the
same host must not crash because the first took the port.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

LOG = logging.getLogger("horovod_tpu.telemetry_http")

_lock = threading.Lock()
_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None


class _Handler(BaseHTTPRequestHandler):
    # The default handler logs every request to stderr — a scraper at
    # 1 Hz would drown the training logs.
    def log_message(self, fmt, *args):  # noqa: D102 - stdlib override
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib naming
        path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
        try:
            if path == "/metrics":
                from horovod_tpu.core import telemetry

                body = telemetry.prometheus()
                try:
                    # Per-rank-labeled world series (rank 0 with the
                    # fleet plane up; empty string elsewhere). A broken
                    # rollup must not take /metrics down with it.
                    from horovod_tpu.core import fleet

                    body += fleet.prometheus_extra()
                except Exception:
                    LOG.debug("fleet prometheus append failed",
                              exc_info=True)
                self._send(200, body.encode(),
                           "text/plain; version=0.0.4")
            elif path == "/healthz":
                from horovod_tpu.core import sentinel

                h = sentinel.health()
                self._send(200 if h["status"] in ("ok", "init") else 503,
                           (json.dumps(h) + "\n").encode(),
                           "application/json")
            elif path == "/fleet":
                from horovod_tpu.core import fleet

                self._send(200,
                           (json.dumps(fleet.fleet_report()) + "\n")
                           .encode(),
                           "application/json")
            elif path == "/doctor":
                # On-demand hang diagnosis (core/doctor.py): publish
                # this rank's inspect table and diff it against every
                # visible peer snapshot — `curl :port/doctor` is the
                # remote spelling of hvd.diagnose().
                from horovod_tpu.core import doctor

                self._send(200,
                           (json.dumps(doctor.diagnose()) + "\n")
                           .encode(),
                           "application/json")
            else:
                self._send(404, b"not found: try /metrics, /healthz, "
                                b"/fleet or /doctor\n",
                           "text/plain")
        except Exception as exc:  # serving must never kill the thread
            try:
                self._send(500, f"error: {exc}\n".encode(), "text/plain")
            except OSError:
                pass  # client went away mid-reply


def maybe_start(port: int) -> Optional[int]:
    """Start the endpoint once; returns the bound port (``port=0`` lets
    the OS pick — tests use this), the already-running port on a second
    call, or None when binding failed (warned once, never raises)."""
    global _server, _thread
    with _lock:
        if _server is not None:
            return _server.server_address[1]
        try:
            srv = ThreadingHTTPServer(("127.0.0.1", int(port)), _Handler)
        except OSError as exc:
            LOG.warning("HVD_TELEMETRY_PORT=%s: cannot bind (%s); "
                        "telemetry endpoint disabled", port, exc)
            return None
        srv.daemon_threads = True
        _server = srv
        _thread = threading.Thread(target=srv.serve_forever,
                                   name="hvd-telemetry-http", daemon=True)
        _thread.start()
        LOG.info("telemetry endpoint on http://127.0.0.1:%d "
                 "(/metrics, /healthz, /fleet, /doctor)",
                 srv.server_address[1])
        return srv.server_address[1]


def stop():
    """Shut the endpoint down (tests only — production lets the daemon
    thread die with the process)."""
    global _server, _thread
    with _lock:
        srv, _server, _thread = _server, None, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()


def current_port() -> Optional[int]:
    with _lock:
        return _server.server_address[1] if _server else None
