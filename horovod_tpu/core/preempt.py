"""Graceful preemption — the planned-eviction half of the fault story.

The elastic stack (core/elastic.py) handles *death*; this module handles
the platform politely asking for the machine back. At pod scale (MLPerf-
class runs, arxiv 1909.09756) maintenance eviction is a routine event
delivered as SIGTERM with a grace window before SIGKILL — the reference
framework simply dies and re-trains from the last manual checkpoint
(arxiv 1802.05799 has no preemption story). Here the ladder is:

1. **Signal intake.** :func:`install` (the keras Trainer calls it at
   ``fit`` start) chains a SIGTERM handler that records the request;
   :func:`requested` is the cheap per-batch poll. The deterministic twin
   is the ``preempt.signal`` faultline site (``core/faultline.py``):
   armed identically on every rank, the lockstep batch count makes the
   whole ladder testable without racing a real signal.
2. **Step drain.** The trainer finishes the in-flight step, bounded by
   ``HVD_PREEMPT_STEP_DEADLINE_S`` — a step wedged behind a dead peer is
   deadline-ABORTED, not waited out (the launcher's ``--grace-s``
   SIGKILL escalation is the backstop either way).
3. **Engine quiesce.** ``engine.quiesce``: admission closes (submits
   fail fast, ``/healthz`` says ``draining``), in-flight collectives
   complete, the report says what drained.
4. **Emergency checkpoint.** The trainer's crash-atomic save (tmp +
   fsync + rename — a SIGKILL mid-save can never corrupt the newest
   checkpoint), into the elastic/`HVD_CHECKPOINT_DIR` location the
   relaunch already resumes from.
5. **Drain barrier.** A KV rendezvous with a deadline
   (``HVD_PREEMPT_BARRIER_S``): no rank exits while a peer still needs
   it for the checkpoint's globalize collective; a peer that never
   arrives (already dead) times the barrier out rather than wedging the
   exit.
6. **Exit 0** with a journaled ``preempted`` note under the elastic dir
   (or ``HVD_PREEMPT_DIR``), so the supervisor/operator can tell a
   graceful eviction from a crash at a glance.

Everything here is stdlib-only on the intake path; jax and the KV plane
are imported only when the ladder actually runs.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Optional

from horovod_tpu.core import faultline as _flt
from horovod_tpu.core import telemetry as _tele
from horovod_tpu.core.sentinel import _env_float

LOG = logging.getLogger("horovod_tpu.preempt")


class PreemptRequested(Exception):
    """Raised out of a training epoch when a preemption request landed;
    the trainer catches it and runs the graceful ladder."""


def step_deadline_s() -> float:
    """Budget for finishing (or abandoning) the in-flight step and the
    emergency checkpoint, each."""
    return _env_float("HVD_PREEMPT_STEP_DEADLINE_S", 30.0)


def barrier_s() -> float:
    """Drain-barrier rendezvous deadline: how long an exiting rank waits
    for its peers to reach the barrier before giving up and exiting
    anyway (a dead peer must not wedge the graceful exit)."""
    return _env_float("HVD_PREEMPT_BARRIER_S", 30.0)


_requested = threading.Event()
_request_reason: Optional[str] = None
_install_lock = threading.Lock()
_installed = False
_prev_handler = None
_counted = False


def _count_request():
    """Increment ``preempt.requested`` exactly once per request — OUT of
    the signal handler: the telemetry registry's locks are non-reentrant
    and the main thread (where CPython runs handlers) routinely holds
    them mid-increment; touching them from the handler could deadlock
    the rank exactly on the eviction path."""
    global _counted
    if _counted:
        return
    _counted = True
    try:
        _tele.REGISTRY.counter("preempt.requested").inc()
    except Exception:
        pass


def _on_sigterm(signum, frame):
    # Async-signal-safe on purpose: set the flag/reason and nothing
    # else (no locks, no logging, no telemetry — _count_request runs
    # later, from requested()/the ladder, in normal thread context).
    global _request_reason
    if not _requested.is_set():
        _request_reason = "SIGTERM"
        _requested.set()
    if callable(_prev_handler):
        # Chain an application handler (SIG_DFL/SIG_IGN are ints) — the
        # graceful ladder is additive, never a replacement.
        try:
            _prev_handler(signum, frame)
        except Exception:
            pass


def install():
    """Install the SIGTERM intake (idempotent; main thread only — the
    signal module's rule; elsewhere the request is still observable via
    the faultline site and an earlier main-thread install)."""
    global _installed, _prev_handler
    with _install_lock:
        if _installed:
            return
        try:
            _prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            _installed = True
        except (ValueError, AttributeError, OSError):
            pass  # non-main thread, or a platform without SIGTERM


def request(reason: str = "requested programmatically"):
    """Arm the preemption request without a signal (tests, custom
    schedulers)."""
    global _request_reason
    if not _requested.is_set():
        _request_reason = reason
        _requested.set()
    _count_request()


def reset():
    """Tests only: clear a standing request."""
    global _request_reason, _counted
    _requested.clear()
    _request_reason = None
    _counted = False


def requested() -> bool:
    """The per-batch poll: True once a SIGTERM (or the deterministic
    ``preempt.signal`` faultline site) asked this process to drain.
    Zero-overhead when nothing is armed: an Event read plus faultline's
    is-None fast path."""
    if _requested.is_set():
        _count_request()  # deferred from the signal handler
        return True
    if _flt.preempt_signal():
        request("injected fault at preempt.signal")
        return True
    return False


def reason() -> Optional[str]:
    return _request_reason


def bounded(fn, deadline_s_: float, what: str):
    """Run ``fn`` on a worker thread, waiting at most ``deadline_s_``.
    Returns (ok, value). A timed-out call is ABANDONED (the thread is
    daemonic and parks — the leak-the-wedged doctrine): a step or
    checkpoint wedged behind a dead peer must not wedge the exit; the
    launcher's grace escalation is the backstop."""
    box = {}
    done = threading.Event()

    def _run():
        try:
            box["value"] = fn()
        except BaseException as exc:  # surfaced as a failed drain
            box["error"] = exc
        done.set()

    t = threading.Thread(target=_run, name=f"hvd-preempt-{what}",
                         daemon=True)
    t.start()
    if not done.wait(max(0.0, deadline_s_)):
        LOG.error("graceful preemption: %s did not finish within %.1fs "
                  "— abandoned (deadline-aborted)", what, deadline_s_)
        return False, None
    if "error" in box:
        LOG.error("graceful preemption: %s failed: %s", what,
                  box["error"])
        return False, None
    return True, box.get("value")


def _barrier_kv():
    """The KV plane for the drain barrier: the coordination-service KV
    when reachable, else the elastic file plane, else None (single
    process, or nothing to rendezvous through)."""
    try:
        from horovod_tpu.core import coordinator as _coord

        return _coord.JaxKV()
    except Exception:
        pass
    try:
        from horovod_tpu.core import elastic as _elastic

        d = _elastic.elastic_dir()
        if d:
            return _elastic.FileKV(os.path.join(d, "kv"))
    except Exception:
        pass
    return None


def drain_barrier(deadline_s_: Optional[float] = None) -> bool:
    """Rendezvous with every peer before exiting: each process marks
    ``hvd/preempt/<gen>/p<i>`` and polls for the others until the
    deadline. True = every peer arrived; False = timed out (exit anyway
    — a peer that never arrives is dead or was never preempted, and
    wedging the exit would just convert a graceful drain into the
    launcher's SIGKILL escalation)."""
    if deadline_s_ is None:
        deadline_s_ = barrier_s()
    try:
        from horovod_tpu.common import topology as _topo

        if not _topo.is_initialized() or _topo.num_processes() <= 1:
            return True
        nproc = _topo.num_processes()
        pid = _topo.process_index()
    except Exception:
        return True
    kv = _barrier_kv()
    if kv is None:
        LOG.warning("graceful preemption: no KV plane for the drain "
                    "barrier; exiting unbarriered")
        return False
    gen = os.environ.get("HVD_ELASTIC_GENERATION", "0")
    ns = f"hvd/preempt/g{gen}"
    stamp = str(round(time.time(), 3))
    try:
        # The coordination-service KV is insert-only: delete-then-set
        # makes the mark idempotent; the file plane overwrites in place.
        try:
            kv.delete(f"{ns}/p{pid}")
        except Exception:
            pass
        kv.set(f"{ns}/p{pid}", stamp)
    except Exception as exc:
        LOG.warning("graceful preemption: cannot publish the drain-"
                    "barrier mark (%s); exiting unbarriered", exc)
        return False
    deadline = time.monotonic() + max(0.0, deadline_s_)
    waiting = [p for p in range(nproc) if p != pid]
    while waiting and time.monotonic() < deadline:
        still = []
        for p in waiting:
            try:
                if kv.try_get(f"{ns}/p{p}") is None:
                    still.append(p)
            except Exception:
                still.append(p)
        waiting = still
        if waiting:
            time.sleep(0.05)
    if waiting:
        LOG.warning("graceful preemption: drain barrier timed out after "
                    "%.1fs still waiting for process(es) %s; exiting "
                    "anyway", deadline_s_, waiting)
        return False
    return True


def journal_note(**extra) -> Optional[str]:
    """Write the per-rank ``preempted`` note (the supervisor/operator's
    evidence that this exit was a graceful eviction, not a crash) under
    ``<elastic dir>/preempt/`` or ``HVD_PREEMPT_DIR``. Returns the path
    or None."""
    base = None
    try:
        from horovod_tpu.core import elastic as _elastic

        base = _elastic.elastic_dir()
    except Exception:
        pass
    base = os.environ.get("HVD_PREEMPT_DIR") or base
    if not base:
        return None
    pid = 0
    try:
        from horovod_tpu.core import timeline as _tl

        pid = _tl._process_index()
    except Exception:
        pass
    note = dict(kind="preempted", process=pid,
                reason=_request_reason or "unknown",
                wall=round(time.time(), 3), **extra)
    try:
        d = os.path.join(base, "preempt")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"p{pid}.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(note, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path
    except OSError as exc:
        LOG.warning("cannot write the preempted journal note: %s", exc)
        return None
