"""Process-wide telemetry: the one queryable answer to "how many
collectives ran, how big, how long, and who was late".

The framework has three execution paths — the compiled SPMD hot path
(hvd.jax.jit), the Python engine and the native C++ engine — and, before
this module, three disconnected lenses on them (chrome timeline, xplane
HBM tables, bench.py's JSON line). This registry is the common sink every
layer feeds (reference rationale: Horovod's production story leaned on
exactly this instrumentation — timeline + stall/straggler analysis,
arxiv 1802.05799 §5; step-time/traffic accounting is what turns a
one-chip benchmark into a scalable system, arxiv 1909.09756):

- :mod:`horovod_tpu.ops.collectives` counts per-op eager calls, bytes and
  world-size-1 elisions;
- :mod:`horovod_tpu.core.engine` (and the native engine through its stats
  C API) counts submissions, completions, errors, fusion-buffer batches
  and cycle time, and times negotiation rounds;
- :mod:`horovod_tpu.core.coordinator` distills per-process lateness from
  the negotiation round tables (the RANK_READY data) into the straggler
  report;
- :func:`horovod_tpu.jax.jit` and the keras Trainer record dispatch /
  step-time ring buffers for the compiled path.

Four surfaces:

- ``hvd.telemetry()`` — nested dict snapshot (this module's
  :func:`telemetry`);
- ``hvd.telemetry_report()`` — human table (:func:`report`);
- ``HVD_TELEMETRY_FILE=<path>`` — Prometheus-style text exposition,
  flushed every ``HVD_TELEMETRY_INTERVAL`` seconds (default 15) and at
  exit;
- ``python -m horovod_tpu.utils.stats <file-or-live>`` — CLI over the
  exposition file (or an xplane capture dir / the live process).

No new dependencies; everything here is stdlib. All mutators are
thread-safe (engine background threads, framework threads and watchdogs
all feed the same registry).
"""

from __future__ import annotations

import atexit
import math
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Default bucket boundaries. Latencies span 100 µs (an engine cycle slice)
# to 30 s (a stalled negotiation); bytes span 256 B (a scalar metric) to
# 1 GiB (a fused gradient buffer).
LATENCY_BUCKETS_S = (1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3,
                     1.0, 3.0, 10.0, 30.0)
BYTES_BUCKETS = tuple(256 * 4 ** i for i in range(12))  # 256 B .. 1 GiB


def quantile_from_buckets(bounds: Sequence[float], counts: Sequence[int],
                          q: float) -> Optional[float]:
    """Quantile estimate from raw histogram bucket counts
    (``len(counts) == len(bounds) + 1``, overflow last). Log-interpolates
    inside the winning bucket — the latency buckets are log-spaced, so
    linear interpolation would bias every estimate toward the upper
    edge. The overflow bucket reports the last bound (a lower bound on
    the true value). None when the histogram is empty."""
    if len(counts) != len(bounds) + 1:
        raise ValueError(
            f"{len(counts)} counts for {len(bounds)} bounds")
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if c and cum >= target:
            if i >= len(bounds):
                return float(bounds[-1])
            hi = float(bounds[i])
            if i > 0:
                lo = float(bounds[i - 1])
            elif len(bounds) > 1:
                lo = hi * float(bounds[0]) / float(bounds[1])
            else:
                lo = hi / 2.0
            frac = (target - (cum - c)) / c
            return float(math.exp(
                math.log(lo) + frac * (math.log(hi) - math.log(lo))))
    return float(bounds[-1])  # pragma: no cover - cum >= target above


class Counter:
    """Monotonic counter (int or float increments)."""

    kind = "counter"
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, occupancy)."""

    kind = "gauge"
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self.value = v

    def snapshot(self):
        return self.value


class Histogram:
    """Fixed-bucket histogram (no dynamic resizing — bounded memory, no
    allocation on the observe path)."""

    kind = "histogram"
    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds: Tuple[float, ...] = LATENCY_BUCKETS_S):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float):
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def observe_many(self, vals):
        """Fold a whole batch of observations under ONE lock acquisition
        (the batched-submit telemetry path: per-value observe() would put
        N lock round-trips back on the submit fast path)."""
        if not vals:
            return
        n_bounds = len(self.bounds)
        idxs = []
        total = 0.0
        for v in vals:
            i = 0
            for i, b in enumerate(self.bounds):
                if v <= b:
                    break
            else:
                i = n_bounds
            idxs.append(i)
            total += v
        with self._lock:
            for i in idxs:
                self.counts[i] += 1
            self.sum += total
            self.count += len(idxs)

    def add_counts(self, deltas: Sequence[int], sum_delta: float = 0.0):
        """Fold per-bucket count deltas (``len(self.counts)`` entries,
        overflow last) plus the matching value-sum delta — the native
        engine's latency sync path: the C++ side observed into its own
        bucket array and hands over deltas, exactly like the stats
        counters, so the merged histogram stays exact (same buckets,
        summed counts)."""
        if len(deltas) != len(self.counts):
            raise ValueError(
                f"bucket-count mismatch: {len(deltas)} deltas for "
                f"{len(self.counts)} buckets")
        with self._lock:
            n = 0
            for i, d in enumerate(deltas):
                self.counts[i] += d
                n += d
            self.sum += sum_delta
            self.count += n

    def snapshot(self):
        with self._lock:
            buckets = {}
            cum = 0
            for b, c in zip(self.bounds, self.counts):
                cum += c
                if c:
                    buckets[b] = cum
            return {"count": self.count, "sum": self.sum,
                    "buckets": buckets}

    def cumulative(self):
        """(bounds, cumulative counts, total count, sum) read atomically —
        the exposition writer must not mix a locked snapshot with a
        second unlocked read of the live counts, or a concurrent observe
        lands a non-monotonic bucket series on a scraper."""
        with self._lock:
            cums, cum = [], 0
            for c in self.counts[:-1]:
                cum += c
                cums.append(cum)
            return self.bounds, cums, self.count, self.sum


class Ring:
    """Fixed-size ring buffer of recent observations (dispatch latencies,
    step times) — bounded memory, summarized at snapshot."""

    kind = "ring"
    __slots__ = ("_buf", "count", "total", "_lock")

    def __init__(self, size: int = 256):
        self._buf = deque(maxlen=size)
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def push(self, v: float):
        with self._lock:
            self._buf.append(v)
            self.count += 1
            self.total += v

    def values(self) -> List[float]:
        """The current window, oldest first (the fleet snapshot ships
        this for the console's step-time sparkline)."""
        with self._lock:
            return list(self._buf)

    def snapshot(self):
        with self._lock:
            window = list(self._buf)
        if not window:
            return {"count": 0}
        return {"count": self.count, "last": window[-1],
                "mean": sum(window) / len(window), "max": max(window),
                "window": len(window)}


class StragglerTracker:
    """Per-process cumulative imposed wait, distilled from the negotiation
    round tables (the same per-process readiness data the timeline's
    RANK_READY instants draw; reference: timeline.cc:106-130 +
    CheckForStalledTensors, operations.cc:1535-1581).

    For each tensor instance the coordinator hands us the time every
    process's announcement was first observed; process ``p`` is charged
    ``t_p - min(t)`` — the microseconds it kept the earliest-ready
    process waiting. Charges accumulate per process and per tensor
    *class* (the name with digits collapsed, so ``grad/17`` and
    ``grad/18`` aggregate)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.tensors = 0
        self.wait_us: Dict[int, int] = {}
        self.by_class: Dict[str, Dict[int, int]] = {}
        self._total_us = 0  # running sum of wait_us values (O(1) reads)

    def observe(self, name: str, announce_times: Dict[int, float]):
        if len(announce_times) < 2:
            return
        t0 = min(announce_times.values())
        cls = re.sub(r"\d+", "#", name)
        with self._lock:
            self.tensors += 1
            per_cls = self.by_class.setdefault(cls, {})
            for pid, t in announce_times.items():
                us = int((t - t0) * 1e6)
                self.wait_us[pid] = self.wait_us.get(pid, 0) + us
                per_cls[pid] = per_cls.get(pid, 0) + us
                self._total_us += us

    def total_wait_us(self) -> int:
        """Cumulative imposed wait across all processes — O(1), no map
        copies (the sentinel reads this on every observed step)."""
        with self._lock:
            return self._total_us

    def worst(self) -> Optional[Tuple[int, int]]:
        """(process, cumulative µs) of the rank that imposed the most
        wait, or None when nothing has been observed."""
        with self._lock:
            if not any(self.wait_us.values()):
                return None
            pid = max(self.wait_us, key=self.wait_us.get)
            return pid, self.wait_us[pid]

    def worst_line(self) -> str:
        """Stall-warning suffix naming the worst straggler (one phrasing
        shared by both engines' watchdogs and the coordinator), or ''."""
        worst = self.worst()
        if worst is None:
            return ""
        return (f"[historically slowest: process {worst[0]}, "
                f"{worst[1] / 1e3:.0f} ms cumulative imposed wait]")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tensors": self.tensors,
                "wait_us": dict(self.wait_us),
                "by_class": {c: dict(v) for c, v in self.by_class.items()},
            }

    def report_lines(self) -> List[str]:
        snap = self.snapshot()
        if not snap["tensors"]:
            return []
        out = [f"straggler report ({snap['tensors']} tensors observed):"]
        for pid, us in sorted(snap["wait_us"].items(),
                              key=lambda kv: -kv[1]):
            out.append(f"  process {pid}: kept the world waiting "
                       f"{us / 1e3:.1f} ms cumulative")
        for cls, per in sorted(snap["by_class"].items()):
            top = max(per, key=per.get)
            if per[top]:
                out.append(f"  {cls}: slowest process {top} "
                           f"(+{per[top] / 1e3:.1f} ms)")
        return out

    def reset(self):
        with self._lock:
            self.tensors = 0
            self.wait_us.clear()
            self.by_class.clear()
            self._total_us = 0


class Registry:
    """Name → metric store. Metric names are dotted paths
    (``engine.submitted.allreduce``); :meth:`snapshot` nests on the dots.
    ``sync`` callbacks let sources that cannot push per-event (the C++
    engine's counters) fold their state in right before a read."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._syncs: List[Callable[[], None]] = []

    # -- metric accessors (get-or-create) -----------------------------------

    def _get(self, name: str, factory):
        # Any metric touch arms the HVD_TELEMETRY_FILE exporter: engine-
        # only or compiled-only workloads must produce the exposition
        # file too, not just paths that happen to call telemetry().
        # Cost once armed/absent: one global-flag check.
        _maybe_start_exporter()
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def counter(self, name: str) -> Counter:
        m = self._get(name, Counter)
        if not isinstance(m, Counter):
            raise TypeError(f"{name} is a {m.kind}, not a counter")
        return m

    def gauge(self, name: str) -> Gauge:
        m = self._get(name, Gauge)
        if not isinstance(m, Gauge):
            raise TypeError(f"{name} is a {m.kind}, not a gauge")
        return m

    def histogram(self, name: str,
                  bounds: Tuple[float, ...] = LATENCY_BUCKETS_S) -> Histogram:
        m = self._get(name, lambda: Histogram(bounds))
        if not isinstance(m, Histogram):
            raise TypeError(f"{name} is a {m.kind}, not a histogram")
        return m

    def ring(self, name: str, size: int = 256) -> Ring:
        m = self._get(name, lambda: Ring(size))
        if not isinstance(m, Ring):
            raise TypeError(f"{name} is a {m.kind}, not a ring")
        return m

    # -- sync hooks (pull-model sources: the native engine) ------------------

    def register_sync(self, fn: Callable[[], None]):
        with self._lock:
            if fn not in self._syncs:
                self._syncs.append(fn)

    def unregister_sync(self, fn: Callable[[], None]):
        with self._lock:
            if fn in self._syncs:
                self._syncs.remove(fn)

    def _run_syncs(self):
        with self._lock:
            syncs = list(self._syncs)
        for fn in syncs:
            try:
                fn()
            except Exception:
                pass  # a dying engine must not take a snapshot down

    # -- views ---------------------------------------------------------------

    def flat(self) -> Dict[str, object]:
        """{dotted name: snapshot value} for every metric (post-sync)."""
        self._run_syncs()
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def flat_counters(self) -> Dict[str, object]:
        """Counters only (post-sync) — the delta-comparable subset."""
        self._run_syncs()
        with self._lock:
            items = [(n, m) for n, m in self._metrics.items()
                     if isinstance(m, Counter)]
        return {name: m.snapshot() for name, m in items}

    def flat_gauges(self) -> Dict[str, object]:
        """Gauges only (post-sync) — the spread-comparable subset the
        fleet rollup reports min/max over (queue depth, pool bytes)."""
        self._run_syncs()
        with self._lock:
            items = [(n, m) for n, m in self._metrics.items()
                     if isinstance(m, Gauge)]
        return {name: m.snapshot() for name, m in items}

    def histogram_counts(self) -> Dict[str, dict]:
        """{name: {bounds, counts (raw, overflow last), sum, count}} for
        every histogram (post-sync) — the mergeable form the fleet
        snapshot publishes: same buckets on every rank, so the world
        rollup sums counts exactly."""
        self._run_syncs()
        with self._lock:
            items = [(n, m) for n, m in self._metrics.items()
                     if isinstance(m, Histogram)]
        out: Dict[str, dict] = {}
        for name, m in items:
            with m._lock:
                out[name] = {"bounds": list(m.bounds),
                             "counts": list(m.counts),
                             "sum": m.sum, "count": m.count}
        return out

    def ring_values(self) -> Dict[str, List[float]]:
        """{name: recent window} for every ring — the fleet snapshot's
        sparkline feed (step times, dispatch latencies)."""
        with self._lock:
            items = [(n, m) for n, m in self._metrics.items()
                     if isinstance(m, Ring)]
        return {name: m.values() for name, m in items}

    def snapshot(self) -> dict:
        """Nested dict of every metric (dots become nesting levels)."""
        flat = self.flat()
        out: dict = {}
        for name, val in flat.items():
            parts = name.split(".")
            d = out
            ok = True
            for p in parts[:-1]:
                nxt = d.setdefault(p, {})
                if not isinstance(nxt, dict):  # name-prefix collision
                    ok = False
                    break
                d = nxt
            if ok and not isinstance(d.get(parts[-1]), dict):
                d[parts[-1]] = val
            elif not isinstance(out.get(name), dict):
                out[name] = val  # keep the flat name instead
            # else: a single-segment name colliding with its own subtree
            # ('a' vs 'a.b') — drop the scalar rather than clobber the
            # subtree. Avoid prefix-colliding metric names.
        return out

    def report(self) -> str:
        """Human-readable table of this registry's metrics (the module
        level :func:`report` adds the process straggler lines)."""
        flat = self.flat()
        if not flat:
            return "telemetry: no metrics recorded"
        out = [f"{'metric':44s} {'value':>16s}"]
        for name in sorted(flat):
            m = flat[name]
            if isinstance(m, dict):
                if "buckets" in m:  # histogram
                    mean = m["sum"] / m["count"] if m["count"] else 0.0
                    val = f"n={m['count']} mean={mean:.6g}"
                elif "count" in m:  # ring
                    val = (f"n={m['count']} last={m.get('last', 0):.6g} "
                           f"mean={m.get('mean', 0):.6g}"
                           if m["count"] else "n=0")
                else:
                    val = str(m)
            elif isinstance(m, float):
                val = f"{m:.6g}"
            else:
                val = str(m)
            out.append(f"{name:44s} {val:>16s}")
        return "\n".join(out)

    def prometheus(self) -> str:
        """Prometheus-style text exposition of the registry (the format
        ``HVD_TELEMETRY_FILE`` writes and ``utils.stats`` parses)."""
        self._run_syncs()
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in items:
            pname = "hvd_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {m.snapshot()}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {m.snapshot()}")
            elif isinstance(m, Histogram):
                bounds, cums, count, total = m.cumulative()
                lines.append(f"# TYPE {pname} histogram")
                for b, cum in zip(bounds, cums):
                    lines.append(f'{pname}_bucket{{le="{b:g}"}} {cum}')
                lines.append(f'{pname}_bucket{{le="+Inf"}} {count}')
                lines.append(f"{pname}_sum {total:.9g}")
                lines.append(f"{pname}_count {count}")
            elif isinstance(m, Ring):
                s = m.snapshot()
                lines.append(f"# TYPE {pname}_count counter")
                lines.append(f"{pname}_count {s['count']}")
                if s["count"]:
                    lines.append(f"# TYPE {pname}_last gauge")
                    lines.append(f"{pname}_last {s['last']:.9g}")
                    lines.append(f"{pname}_mean {s['mean']:.9g}")
        return "\n".join(lines) + "\n"

    def reset(self):
        """Drop every metric (tests only — sync hooks stay registered)."""
        with self._lock:
            self._metrics.clear()
        STRAGGLERS.reset()


REGISTRY = Registry()
STRAGGLERS = StragglerTracker()


def telemetry() -> dict:
    """Nested snapshot of every counter/gauge/histogram/ring plus the
    process straggler report — the ``hvd.telemetry()`` surface. (The
    straggler merge lives here, not in Registry: standalone Registry
    instances must not report the process-global tracker's data.)"""
    _maybe_start_exporter()
    out = REGISTRY.snapshot()
    strag = STRAGGLERS.snapshot()
    if strag["tensors"]:
        out["straggler"] = strag
    world = _world_lines(as_dict=True)
    if world:
        out["world"] = world
    return out


def _world_lines(as_dict: bool = False):
    """Elastic world state (core/elastic.py world.* gauges) for the
    report surfaces: None/[] when the process is not an elastic world
    member."""
    try:
        from horovod_tpu.core import elastic as _elastic

        world = _elastic.world_summary()
    except Exception:  # pragma: no cover - defensive
        world = None
    if as_dict:
        return world
    if world is None:
        return []
    line = (f"world: epoch {world['epoch']} "
            f"size {world['size']} "
            f"({world['processes']}/{world['initial_processes']} "
            f"process(es), generation {world['generation']})")
    if world.get("degraded"):
        line += " DEGRADED"
        if world.get("dead"):
            line += " — lost process(es) " + ", ".join(
                str(p) for p in sorted(world["dead"]))
    return [line]


def report() -> str:
    """Human-readable table — the ``hvd.telemetry_report()`` surface."""
    out = REGISTRY.report()
    lines = _world_lines() + STRAGGLERS.report_lines()
    return out + ("\n" + "\n".join(lines) if lines else "")


def compact() -> dict:
    """Small flat summary for embedding in bench.py's single JSON line:
    nonzero counters, ring counts, and per-process straggler waits."""
    out: Dict[str, object] = {}
    for name, val in REGISTRY.flat().items():
        if isinstance(val, (int, float)) and val:
            out[name] = val
        elif isinstance(val, dict) and val.get("count"):
            out[name + ".count"] = val["count"]
    strag = STRAGGLERS.snapshot()
    if strag["tensors"]:
        out["straggler.wait_us"] = strag["wait_us"]
    return out


# ---------------------------------------------------------------------------
# HVD_TELEMETRY_FILE exposition (interval + atexit)
# ---------------------------------------------------------------------------

_exporter_lock = threading.Lock()
_exporter_started = False
_http_started = False


def prometheus() -> str:
    """Process-wide exposition: the global registry plus the straggler
    tracker (what ``HVD_TELEMETRY_FILE`` holds)."""
    lines = [REGISTRY.prometheus().rstrip("\n")]
    strag = STRAGGLERS.snapshot()
    if strag["tensors"]:
        lines.append("# TYPE hvd_straggler_wait_microseconds counter")
        for pid, us in sorted(strag["wait_us"].items()):
            lines.append(
                f'hvd_straggler_wait_microseconds{{process="{pid}"}} {us}')
        lines.append(f"hvd_straggler_tensors {strag['tensors']}")
    return "\n".join(lines) + "\n"


def flush_to_file(path: Optional[str] = None):
    """Write the Prometheus exposition atomically (tmp + replace) so a
    concurrent reader never sees a torn file."""
    path = path or os.environ.get("HVD_TELEMETRY_FILE")
    if not path:
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            fh.write(prometheus())
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _exporter_loop(path: str, interval_s: float):
    while True:
        time.sleep(interval_s)
        flush_to_file(path)


def _maybe_start_http():
    """Start the HVD_TELEMETRY_PORT localhost endpoint once, lazily
    (same activation rule as the file exporter): /metrics serves this
    exposition, /healthz the sentinel's watchdog state — see
    core/telemetry_http.py."""
    global _http_started
    if _http_started:
        return
    port = os.environ.get("HVD_TELEMETRY_PORT")
    if not port:
        return
    with _exporter_lock:
        if _http_started:
            return
        _http_started = True
    try:
        pnum = int(port)
        if pnum <= 0:
            return  # "0" means disabled, NOT an ephemeral port
        from horovod_tpu.core import telemetry_http

        telemetry_http.maybe_start(pnum)
    except Exception:
        pass  # a malformed port / bind failure must not break metrics


def _maybe_start_exporter():
    """Start the HVD_TELEMETRY_FILE flusher once, lazily (first telemetry
    touch) — no thread at import, nothing at all when the env is unset.
    The HTTP endpoint rides the same activation points."""
    global _exporter_started
    _maybe_start_http()
    if _exporter_started:
        return
    path = os.environ.get("HVD_TELEMETRY_FILE")
    if not path:
        return
    with _exporter_lock:
        if _exporter_started:
            return
        _exporter_started = True
        interval = float(os.environ.get("HVD_TELEMETRY_INTERVAL", "15"))
        atexit.register(flush_to_file, path)
        threading.Thread(target=_exporter_loop, args=(path, interval),
                         name="hvd-telemetry-export", daemon=True).start()


def record_eager(op: str, nbytes: int, elided: bool = False):
    """One eager collective call (ops/collectives.py feeds this; the jax
    frontend's size-1 short circuits too)."""
    _maybe_start_exporter()
    REGISTRY.counter(f"eager.{op}.count").inc()
    REGISTRY.counter(f"eager.{op}.bytes").inc(int(nbytes))
    if elided:
        REGISTRY.counter(f"eager.{op}.elided").inc()
