"""Background dispatch engine for async host-side collectives.

Architecture mirrors the reference core (SURVEY.md §2.1 C1-C6): framework
threads *enqueue* named tensors and get an integer handle; one background
thread drains the queue each cycle, fuses compatible requests into flat
buffers, executes them on the data plane, and completes handles
(reference: operations.cc BackgroundThreadLoop/RunLoopOnce:1921-2172,
EnqueueTensorAllreduce:2264-2300, HandleManager: torch/handle_manager.cc).

TPU-native differences:
- No rank-0 negotiation: within one controller, request order is the
  program order; consistency checks (dtype/shape/op agreement for a name)
  still run and surface the reference's ERROR semantics
  (operations.cc:315-517).
- The data plane is the XLA collective module (:mod:`horovod_tpu.ops`),
  so "execute" stages host tensors onto the mesh — the same staging shape
  as the reference's CudaOnCPU path (torch/mpi_ops_v2.cc:78-110).

This Python engine is the semantic reference; the C++ `libhvdcore` engine
(horovod_tpu/core/native) replaces the scheduler/table/fusion loop with the
same observable behavior.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from horovod_tpu.core import bufferpool as bpool
from horovod_tpu.core import faultline as flt
from horovod_tpu.core import numerics as numx
from horovod_tpu.core import telemetry as tele
from horovod_tpu.core import timeline as tl

LOG = logging.getLogger("horovod_tpu.engine")

DEFAULT_CYCLE_TIME_S = 0.005  # reference: 5 ms, operations.cc:1747
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024  # reference: 64 MB, operations.cc:1739
STALL_WARNING_TIME_S = 60.0  # reference: operations.cc:253

# Engine-side wire formats (the quantized-collectives subsystem,
# jax/quantize.py): applied per execution CHUNK in the shared data plane
# below, so the python and C++ engines produce bit-identical reductions
# under the same policy by construction. Cast policies (bf16/fp16) stay
# frontend-side — they ride compress()/decompress() around the submit.
# Codes are the `wire` field of the C ABI (hvdcore.cc hvd_request).
ENGINE_WIRE_POLICIES = ("none", "int8", "fp8")
WIRE_CODES = {name: i for i, name in enumerate(ENGINE_WIRE_POLICIES)}
WIRE_NAMES = {i: name for name, i in WIRE_CODES.items()}

# Priority classes on the submit plane (the serving-plane subsystem):
# codes are the `priority` field of the C ABI (hvdcore.cc hvd_request) —
# a LOWER code drains first, so the tuple order below IS the drain
# order. The cycle loop composes fused batches and drains ready work in
# (priority, deadline-margin, name) order; admission budgets
# (HVD_ADMISSION_MAX_*) are accounted per class.
PRIORITY_CLASSES = ("high", "normal", "low")
PRIORITY_CODES = {name: i for i, name in enumerate(PRIORITY_CLASSES)}
PRIORITY_NAMES = {i: name for name, i in PRIORITY_CODES.items()}

# Per-entry introspection record shape (``Engine.inspect`` /
# ``NativeEngine.inspect`` / the ``hvd_engine_inspect`` C ABI): key names
# AND their order are machine-diffed against the C++ Inspect writer by
# hvdcheck rule ``parity-doctor`` — the hang doctor (core/doctor.py)
# correlates these records across ranks, so the two engines must export
# the identical shape. Records are built with ``dict(keyword=...)`` on
# purpose: dict literals in this module are swept by the span-args
# vocabulary lint (hvdcheck parity-span-args).
ENGINE_INSPECT_KEYS = (
    "name", "op", "phase", "phase_age_us", "bytes", "dtype", "wire",
    "batch_n", "priority", "deadline_remaining_us", "round",
)


def _process_str() -> str:
    try:
        from horovod_tpu.common import topology as _topo

        if _topo.is_initialized():
            return f"process {_topo.process_index()}"
    except Exception:
        pass
    return f"pid {os.getpid()}"


def resolve_wire_policy(name: Optional[str]) -> str:
    """Normalize an engine wire-policy spelling, failing FAST with rank
    attribution on unknown names (the same contract the frontend
    Compression surfaces enforce)."""
    if name is None:
        return "none"
    val = str(name).lower()
    if val in ("", "0", "false", "off"):
        return "none"
    if val not in ENGINE_WIRE_POLICIES:
        raise EngineError(
            f"unknown engine wire policy {name!r} on {_process_str()}: "
            f"expected one of {list(ENGINE_WIRE_POLICIES)} (cast "
            "policies bf16/fp16 are applied frontend-side)")
    return val


def wire_policy_from_env() -> str:
    """HVD_COMPRESSION: the engine-wide default wire format for the
    execution chunks (per-request policies override it). Misspellings
    fail fast at engine construction."""
    return resolve_wire_policy(os.environ.get("HVD_COMPRESSION")
                               or os.environ.get("HOROVOD_COMPRESSION"))


def wire_dcn_policy_from_env() -> str:
    """HVD_COMPRESSION_DCN: the engine-wide default DCN-tier wire format
    for the hierarchical two-phase route (per-request
    ``compression_dcn`` overrides it). Inert unless the world has
    two-tier structure AND HVD_HIERARCHICAL_ALLREDUCE is on — a flat
    world never quantizes through it."""
    return resolve_wire_policy(os.environ.get("HVD_COMPRESSION_DCN")
                               or os.environ.get("HOROVOD_COMPRESSION_DCN"))


def check_wire_exclusive(wire: str, wire_dcn: str, name: str):
    """A request's uniform wire policy and its per-tier DCN policy are
    mutually exclusive: `wire` quantizes the WHOLE exchange (the flat
    PR-12 route), `wire_dcn` quantizes only the 1/L cross-tier shard of
    the hierarchical route — asking for both is ambiguous about which
    pipeline runs, so the submit fails fast (shared by both engines)."""
    if wire not in ("", "none") and wire_dcn not in ("", "none"):
        raise EngineError(
            f"request '{name}' on {_process_str()} sets both the uniform "
            f"wire policy ({wire!r}) and the per-tier DCN policy "
            f"({wire_dcn!r}): they are mutually exclusive — the uniform "
            "policy quantizes the whole exchange, the DCN policy "
            "quantizes only the 1/L cross-tier shard of the "
            "hierarchical route. Pick one.")


def _poison_result(fault, out: np.ndarray, private: bool = False) -> np.ndarray:
    """engine.exec 'poison' fault: NaN-fill a float result AFTER the real
    collective ran — the reduced value every rank hands back is poisoned,
    which is what drives the numerics engine_check_result attribution
    (non-float results pass through; there is no NaN to poison with).

    ``private=True`` says the reduction already produced a buffer nothing
    else can alias (the executor's pool-checked-out output), so the
    defensive copy is the double copy on the result path — poison in
    place instead."""
    if fault is None or fault.mode != "poison" or out.dtype.kind not in "fc":
        return out
    if not private:
        out = np.array(out)  # never scribble on a caller-shared buffer
    out[...] = np.nan
    return out


# Placeholder a completed entry's tensor is swapped to (releases the
# snapshot slab's last engine-side reference before the waiter wakes).
_RETIRED = np.empty((0,), np.uint8)


def _freeze_donated(a: np.ndarray) -> bool:
    """Flag a donated buffer unwriteable so a donate-then-mutate raises
    (runtime-owned buffers — jax/TF — are read-only already). Returns
    whether the flag was actually flipped: a REJECTED donated submit
    (duplicate name, shutdown, injected fault) must flip it back — the
    engine never took ownership, and the caller's buffer must not stay
    read-only forever."""
    if not a.flags.writeable:
        return False
    try:
        a.flags.writeable = False
        return True
    except ValueError:  # pragma: no cover — writeable arrays flip fine
        return False


class EngineError(RuntimeError):
    """Collective failed; surfaced at synchronize() like the reference's
    ERROR response → exception path (test_torch.py:265-349)."""


class DuplicateNameError(EngineError):
    """Same tensor name enqueued twice before completion (reference:
    operations.cc:265-268, 2293-2296)."""


class ShutdownError(EngineError):
    """Engine shut down with requests outstanding (reference:
    SHUT_DOWN_ERROR, operations.cc:1833-1848)."""


class AdmissionRejected(EngineError):
    """The serving-plane admission controller rejected this submit
    SYNCHRONOUSLY at the boundary: the request's priority class is at
    its in-flight budget (HVD_ADMISSION_MAX_INFLIGHT /
    HVD_ADMISSION_MAX_BYTES), or the deadline-aware fast-fail shed it
    because its remaining deadline is provably smaller than the current
    p50 queue+negotiate latency. Nothing was admitted — no handle, no
    queue state, no peer announcement — so the caller may retry,
    degrade, or drop; in-flight work is NEVER rejected mid-flight and a
    fused batch is never torn (the cancel doctrine)."""


class CollectiveTimeout(EngineError):
    """A per-request deadline fired before the collective completed. The
    message names the PHASE the entry was stuck in (QUEUE / NEGOTIATE /
    ALLREDUCE / ...) and its age — fail fast with attribution instead of
    waiting out the global negotiation timeout. The entry itself may
    still be in flight (a wedged executor call cannot be interrupted);
    only the waiter is released, and an eventual late completion is
    discarded."""


class CancelledError(EngineError):
    """The collective was cooperatively cancelled (``cancel(handle)``).
    Pre-announce entries retire locally without executing; entries
    already announced to peers (or already executing) complete
    cross-rank — a fused/negotiated batch cannot be torn — and their
    result is discarded, so negotiation coherence is preserved by
    construction."""


def collective_deadline_from_env() -> Optional[float]:
    """HVD_COLLECTIVE_DEADLINE_S: the engine-wide default per-request
    deadline (seconds); per-request ``deadline_ms`` overrides it. Unset,
    empty or <= 0 means no default — and the deadline plane then adds
    ZERO hot-path work (the sweep short-circuits on a zero count)."""
    raw = (os.environ.get("HVD_COLLECTIVE_DEADLINE_S") or "").strip()
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        raise EngineError(
            f"bad HVD_COLLECTIVE_DEADLINE_S {raw!r} on {_process_str()}: "
            "want seconds (a float)") from None
    return val if val > 0 else None


def resolve_priority(priority, name: str = "") -> int:
    """Normalize a priority-class spelling (or its integer code) to the
    code, failing FAST with rank attribution on unknown values — the
    same contract as :func:`resolve_wire_policy`. ``None`` means
    'normal' (callers that defer to the engine default resolve
    HVD_PRIORITY themselves via :func:`priority_from_env`)."""
    if priority is None:
        return PRIORITY_CODES["normal"]
    if isinstance(priority, (int, np.integer)) \
            and int(priority) in PRIORITY_NAMES:
        return int(priority)
    val = str(priority).lower()
    if val in PRIORITY_CODES:
        return PRIORITY_CODES[val]
    raise EngineError(
        f"unknown priority class {priority!r}"
        + (f" for '{name}'" if name else "")
        + f" on {_process_str()}: expected one of "
        f"{list(PRIORITY_CLASSES)} (or a code 0/1/2)")


def priority_from_env() -> int:
    """HVD_PRIORITY: the engine-wide default priority class for submits
    that name none ('normal' when unset). Misspellings fail fast at
    engine construction."""
    raw = (os.environ.get("HVD_PRIORITY")
           or os.environ.get("HOROVOD_PRIORITY") or "").strip()
    return resolve_priority(raw or None)


def _admission_limit(env: str, cls: str) -> int:
    """One per-class admission budget: ``{env}_{CLS}`` overrides the
    class-wide ``{env}``; unset/empty/0 means unlimited."""
    key = f"{env}_{cls.upper()}"
    raw = (os.environ.get(key) or os.environ.get(env) or "").strip()
    if not raw:
        return 0
    try:
        val = int(raw)
    except ValueError:
        raise EngineError(
            f"bad {key if os.environ.get(key) else env} {raw!r} on "
            f"{_process_str()}: want an integer (0 = unlimited)"
        ) from None
    return max(val, 0)


def admission_from_env():
    """HVD_ADMISSION_MAX_INFLIGHT / HVD_ADMISSION_MAX_BYTES: bounded
    per-class queue budgets for the serving plane (admission control).
    Each knob is the default for EVERY class; ``_HIGH`` / ``_NORMAL`` /
    ``_LOW`` suffixes override one class. 0/unset = unlimited (the
    historical behavior). Returns (max_inflight, max_bytes) as lists
    ordered like PRIORITY_CLASSES — shared by both engines; the native
    engine pushes the arrays through ``hvd_engine_set_admission`` at
    construction so its lock-free submit path enforces the same
    budgets."""
    mi = [_admission_limit("HVD_ADMISSION_MAX_INFLIGHT", c)
          for c in PRIORITY_CLASSES]
    mb = [_admission_limit("HVD_ADMISSION_MAX_BYTES", c)
          for c in PRIORITY_CLASSES]
    return mi, mb


# Deadline-aware shedding engages only once the phase histograms hold a
# meaningful sample (a cold engine must not shed on startup noise).
SHED_MIN_SAMPLES = 8


def queue_latency_estimate() -> Optional[float]:
    """Current p50 queue (+ negotiate, when that phase has samples)
    residency in seconds, read from the engine.phase.* histograms — the
    deadline-aware fast-fail's shedding threshold. None until
    SHED_MIN_SAMPLES observations exist, so a cold engine never
    sheds."""
    h = tele.REGISTRY.histogram("engine.phase.queue")
    if h.count < SHED_MIN_SAMPLES:
        return None
    est = tele.quantile_from_buckets(h.bounds, h.counts, 0.5)
    if est is None:
        return None
    hn = tele.REGISTRY.histogram("engine.phase.negotiate")
    if hn.count >= SHED_MIN_SAMPLES:
        neg = tele.quantile_from_buckets(hn.bounds, hn.counts, 0.5)
        if neg is not None:
            est += neg
    return est


@dataclass
class _Entry:
    handle: int
    name: str
    op: str  # 'allreduce' | 'allgather' | 'broadcast'
    tensor: np.ndarray
    average: bool = False
    root_rank: int = 0
    prescale: float = 1.0
    compression: str = "none"  # engine wire policy for this request
    # Per-tier DCN wire policy (hierarchical two-phase route): quantizes
    # ONLY the 1/L cross-tier shard; mutually exclusive with
    # `compression` (check_wire_exclusive at submit).
    compression_dcn: str = "none"
    # Ownership-handoff submit (allreduce_async(..., donate=True)): the
    # entry references the caller's buffer in place — no snapshot copy
    # was taken, and the engine only ever READS it (results land in
    # separate pool buffers), so frontends may donate runtime-owned
    # immutable buffers (jax arrays, TF eager tensors).
    donated: bool = False
    enqueued_at: float = field(default_factory=time.monotonic)
    # Processes whose announcement of this tensor has been marked on the
    # timeline (RANK_READY instants inside the NEGOTIATE_* span).
    ready_marked: set = field(default_factory=set)
    # Deadline/cancel plane: absolute monotonic deadline (None = none),
    # the phase the entry is currently stuck in (QUEUE -> NEGOTIATE ->
    # ALLREDUCE/ALLGATHER/BROADCAST — the CollectiveTimeout attribution),
    # whether the deadline already failed the waiter, and whether a
    # cooperative cancel is pending.
    deadline: Optional[float] = None
    phase: str = tl.QUEUE
    # Monotonic time of the last phase transition: the per-phase
    # residency histograms (engine.phase.*) observe the elapsed span at
    # every transition and once more at completion.
    phase_since: float = field(default_factory=time.monotonic)
    fired: bool = False
    cancelled: bool = False
    # Size of the batched submit this entry rode in on (submit_n /
    # hvd_engine_enqueue_n); 1 for a per-tensor submit. Carried onto the
    # QUEUE/MEMCPY span args so the trace critical path can attribute a
    # batch's queue share per member, not N x.
    batch_n: int = 1
    # Priority class code (PRIORITY_CODES; lower drains first). Joins
    # the drain sort key, the fusion key and — in negotiated worlds —
    # the request fingerprint, so batches stay priority-uniform and
    # mixed-priority worlds for one tensor fail fast by name.
    priority: int = 1


class _Handle:
    __slots__ = ("event", "result", "error", "name")

    def __init__(self, name: str = ""):
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        self.name = name  # numerics attribution at synchronize


class SubmitRequest:
    """One request of a batched submit (``Engine.submit_n`` /
    ``NativeEngine.submit_n``): the per-tensor arguments of the
    ``*_async`` verbs as one value, so a frontend holding a whole
    gradient bucket can hand it over in ONE engine call. Fields that a
    given op ignores (``root_rank`` for allreduce, ``average`` for
    broadcast, ...) are simply unused — exactly as the per-tensor verbs
    treat them. A plain-slots class, not a dict: the span-args
    vocabulary lint (hvdcheck span parity) sweeps dict literals in this
    module."""

    __slots__ = ("name", "tensor", "average", "root_rank", "prescale",
                 "compression", "compression_dcn", "donate", "deadline_ms",
                 "priority")

    def __init__(self, name: str, tensor, *, average: bool = False,
                 root_rank: int = 0, prescale: float = 1.0,
                 compression: Optional[str] = None,
                 compression_dcn: Optional[str] = None, donate: bool = False,
                 deadline_ms: Optional[float] = None,
                 priority: Optional[str] = None):
        self.name = name
        self.tensor = tensor
        self.average = average
        self.root_rank = root_rank
        self.prescale = prescale
        self.compression = compression
        self.compression_dcn = compression_dcn
        self.donate = donate
        self.deadline_ms = deadline_ms
        self.priority = priority


class JaxExecutor:
    """Data plane: host numpy buffers → eager XLA collectives over the mesh
    (reference analogue: PerformOperation's MPI/NCCL calls,
    operations.cc:1401-1531).

    When ``measure_staging`` is on (set by the engines while a timeline is
    being recorded), each call times the host→device staging step and
    leaves it in ``last_stage_s`` — the engines turn it into the
    ``WAIT_FOR_DATA`` span the reference records while waiting for input
    data to become available (operations.cc:783-807)."""

    measure_staging = False
    last_stage_s = 0.0
    # Buffer pool for output/staging buffers (engines hand their own pool
    # over at construction; a standalone executor rides the process-wide
    # default). Output buffers are checked out per call and recycle when
    # the caller drops the result views — the allocation-free
    # steady-state contract of core/bufferpool.py.
    pool = None
    # Wire policy of the CURRENT allreduce call (set by the engine from
    # the request's `compression`/`wire` just before the call — an
    # attribute, not a parameter, so test doubles with the historical
    # allreduce(flat, average) signature keep working) and the bytes the
    # call actually shipped (payload + scales under a quantized policy,
    # full width otherwise). Both engines read these into the
    # engine.wire_bytes{,.compressed} telemetry counters.
    wire_policy = "none"
    last_wire_bytes = 0
    last_wire_compressed = 0
    # Per-tier DCN wire policy of the current call (the hierarchical
    # two-phase route: ICI reduce-scatter at the resident dtype, ONLY
    # the 1/L shard crosses the DCN tier quantized) and the per-tier
    # byte split of the last call. Both stay 0 on every non-hierarchical
    # route — the engines feed them into engine.wire_bytes.dcn/.ici.
    wire_policy_dcn = "none"
    last_wire_bytes_dcn = 0
    last_wire_bytes_ici = 0

    @staticmethod
    def _ctx(arr: np.ndarray):
        # jax downcasts 64-bit dtypes unless x64 is enabled; host tensors
        # (e.g. torch float64 hyperparameters) must round-trip exactly.
        import contextlib

        if arr.dtype.itemsize == 8 and arr.dtype.kind in "fiuc":
            import jax

            if hasattr(jax, "enable_x64"):
                return jax.enable_x64()
            # jax versions without the top-level alias keep the
            # experimental spelling.
            from jax.experimental import enable_x64

            return enable_x64()
        return contextlib.nullcontext()

    def _stage(self, arr: np.ndarray):
        """Host→device transfer (the WAIT_FOR_DATA phase)."""
        import jax.numpy as jnp

        if not self.measure_staging:
            self.last_stage_s = 0.0
            return jnp.asarray(arr)
        t0 = time.perf_counter()
        staged = jnp.asarray(arr)
        try:
            staged.block_until_ready()
        except Exception:
            pass
        self.last_stage_s = time.perf_counter() - t0
        return staged

    # Fused-buffer execution granularity. Runtime fusion concatenates
    # whatever happened to share a cycle, so raw lengths are effectively
    # unique — every length would recompile the eager collective program.
    # Executing in fixed CHUNK-sized slices plus one pow2-bucketed tail
    # bounds the program count to ~12 per dtype (and chunking large
    # buffers also keeps any one staging transfer bounded).
    CHUNK_ELEMS = 1 << 22  # 16 MB of f32 — ~the reference's fusion scale

    @staticmethod
    def _bucket(n: int) -> int:
        """Round a tail length up to the next power of two (≥1 KiB of
        elements): ≤11 distinct tail programs below CHUNK_ELEMS."""
        return max(1024, 1 << (n - 1).bit_length())

    def _checkout(self, count: int, dtype) -> np.ndarray:
        pool = self.pool
        if pool is None:
            pool = self.pool = bpool.get_default()
        return pool.checkout(count, dtype)

    def _quantized_chunk(self, chunk: np.ndarray, pol, average: bool):
        """One execution chunk under a quantized wire policy: quantize
        HOST-side (the staged device buffers — the wire — already carry
        the int8 payload + f32 scales), allgather both across the world
        (each rank's hop ships the quantized bytes, the quantized
        reduce-scatter's per-rank traffic), dequantize-accumulate in
        f32. The quantize step stages into pool-checked-out wire slabs
        (payload, scales, f32 scratch) — no fresh arrays in the
        steady-state wire path. Returns (reduced chunk (f32), wire bytes
        shipped)."""
        from horovod_tpu.jax import quantize as Q
        from horovod_tpu.ops import collectives as C

        npad = Q.padded_len(max(chunk.shape[0], 1), pol.block)
        payload = self._checkout(npad, Q.np_wire_dtype(pol))
        scales = self._checkout(npad // pol.block, np.float32)
        work = self._checkout(npad, np.float32)
        Q.np_quantize_into(chunk, pol, payload, scales, work)
        gp = np.asarray(C.allgather(self._stage(payload)))
        stage_s = self.last_stage_s
        gs = np.asarray(C.allgather(self._stage(scales)))
        self.last_stage_s += stage_s
        world = gp.shape[0] // npad
        out = Q.np_dequantize_sum(gp.reshape(world, npad),
                                  gs.reshape(world, -1), pol)
        if average:
            out /= world
        return out[:chunk.shape[0]], payload.nbytes + scales.nbytes

    def _wire_quantizer(self, flat: np.ndarray):
        """The quantized-policy object for this call, or None (policy
        off, non-float payload, or a 1-rank world — where the compiled
        path elides quantization too, so the engines match)."""
        if self.wire_policy in ("", "none") or flat.dtype.kind not in "f":
            return None
        try:
            from horovod_tpu.common import topology as _topo

            if _topo._require_init().size <= 1:
                return None
        except Exception:
            return None
        from horovod_tpu.jax.compression import Compression

        return Compression.resolve(self.wire_policy, where="engine wire")

    def _dcn_quantizer(self, flat: np.ndarray):
        """The quantized DCN-tier policy for this call, or None. Gated
        exactly like the compiled hierarchical route: float payload, a
        multi-chip world with two-tier structure, the hierarchical knob
        on, AND a cross tier of more than one group — a single-tier
        outer axis elides the quantization (no wire hop to shrink), so
        the digest stays on the unquantized path on both planes."""
        if (self.wire_policy_dcn in ("", "none")
                or flat.dtype.kind not in "f"):
            return None
        try:
            from horovod_tpu.common import topology as _topo
            from horovod_tpu.ops import collectives as C

            st = _topo._require_init()
            if (st.size <= 1 or st.two_tier is None
                    or not C.hierarchical_allreduce_enabled()):
                return None
            # Tier dims come from the two-tier MESH, not the host
            # split: a simulated topology (HVD_TWO_TIER_SHAPE) has
            # several mesh groups inside one host/process.
            if dict(st.two_tier.shape).get("dcn", 1) <= 1:
                return None
        except Exception:
            return None
        from horovod_tpu.jax.compression import Compression

        return Compression.resolve(self.wire_policy_dcn,
                                   where="engine dcn wire")

    @staticmethod
    def _two_tier_chunk_bytes(n: int, dpol) -> int:
        """DCN-tier bytes one execution chunk of ``n`` elements ships on
        the hierarchical route: the 1/L ICI-reduced shard, block-padded
        and quantized (payload + f32 scales) — mirroring
        spmd_allreduce's padding (outer_size * block) so the counter is
        the TRUE cross-tier payload, not an estimate."""
        from horovod_tpu.common import topology as _topo
        from horovod_tpu.jax import quantize as Q

        shape = dict(_topo._require_init().two_tier.shape)
        local = shape["ici"]
        cross = shape["dcn"]
        n_ici = Q.padded_len(max(n, 1), local) // local
        npad = Q.padded_len(n_ici, cross * dpol.block)
        wire_itemsize = np.dtype(Q.np_wire_dtype(dpol)).itemsize
        return npad * wire_itemsize + (npad // dpol.block) * 4

    def allreduce(self, flat: np.ndarray, average: bool) -> np.ndarray:
        from horovod_tpu.ops import collectives as C

        fault = flt.engine_exec("allreduce")  # stall sleeps, error raises
        pol = self._wire_quantizer(flat)
        dpol = self._dcn_quantizer(flat) if pol is None else None
        n = flat.shape[0]
        # Pool-checked-out result buffer: private by construction (nothing
        # else holds a view), handed to callers as slices and recycled by
        # the pool once they drop it.
        out = self._checkout(n, flat.dtype)
        stage_s = 0.0
        wire = 0
        wire_dcn = 0
        wire_ici = 0
        with self._ctx(flat):
            off = 0
            while off < n:
                take = min(self.CHUNK_ELEMS, n - off)
                chunk = flat[off: off + take]
                bucket = (take if take == self.CHUNK_ELEMS
                          else self._bucket(take))
                if bucket != take:
                    # Zero padding is reduction-neutral (sum of zeros;
                    # average divides by world size only — and zero
                    # blocks quantize to zero payload). Padded into a
                    # pooled slab, not a fresh concatenation.
                    padded = self._checkout(bucket, flat.dtype)
                    padded[:take] = chunk
                    padded[take:] = 0
                    chunk = padded
                if pol is not None:
                    res, chunk_wire = self._quantized_chunk(chunk, pol,
                                                            average)
                    wire += chunk_wire
                elif dpol is not None:
                    # Hierarchical two-phase route: the eager ranked
                    # program reduce-scatters over ICI at the resident
                    # dtype and ships ONLY the quantized 1/L shard
                    # across the DCN tier — both engines execute it
                    # through this shared call, so their digests are
                    # bit-identical by construction.
                    res = np.asarray(
                        C.allreduce(self._stage(chunk), average=average,
                                    dcn_wire=self.wire_policy_dcn))
                    ici_b = chunk.nbytes
                    dcn_b = self._two_tier_chunk_bytes(chunk.shape[0],
                                                       dpol)
                    wire += ici_b + dcn_b
                    wire_ici += ici_b
                    wire_dcn += dcn_b
                else:
                    res = np.asarray(
                        C.allreduce(self._stage(chunk), average=average))
                    wire += chunk.nbytes
                stage_s += self.last_stage_s
                out[off: off + take] = res[:take]
                off += take
        self.last_stage_s = stage_s
        self.last_wire_bytes = wire
        self.last_wire_compressed = (wire if pol is not None else wire_dcn)
        self.last_wire_bytes_dcn = wire_dcn
        self.last_wire_bytes_ici = wire_ici
        return _poison_result(fault, out, private=True)

    def allgather(self, tensor: np.ndarray) -> np.ndarray:
        from horovod_tpu.ops import collectives as C

        fault = flt.engine_exec("allgather")
        self.last_wire_bytes = tensor.nbytes
        self.last_wire_compressed = 0
        self.last_wire_bytes_dcn = 0
        self.last_wire_bytes_ici = 0
        with self._ctx(tensor):
            return _poison_result(
                fault, np.asarray(C.allgather(self._stage(tensor))))

    def broadcast(self, tensor: np.ndarray, root_rank: int) -> np.ndarray:
        from horovod_tpu.ops import collectives as C

        fault = flt.engine_exec("broadcast")
        self.last_wire_bytes = tensor.nbytes
        self.last_wire_compressed = 0
        self.last_wire_bytes_dcn = 0
        self.last_wire_bytes_ici = 0
        with self._ctx(tensor):
            return _poison_result(
                fault,
                np.asarray(C.broadcast(self._stage(tensor), root_rank)))


def _multi_controller() -> bool:
    """True when more than one controller process is active. Fusion
    decisions are local to a controller; with several controllers, local
    drain timing could fuse different batches on different processes and
    launch mismatched collective programs — the failure the reference's
    rank-0 negotiation exists to prevent (operations.cc:279-517). The
    negotiated path (core/coordinator.py) makes batch composition agreed;
    without it, multi-process runs execute one name-ordered collective
    per tensor."""
    try:
        from horovod_tpu.common import topology as _topo

        return _topo.is_initialized() and _topo.num_processes() > 1
    except Exception:
        return False


def _negotiated() -> bool:
    """True when multi-controller runs will coordinate batches through the
    KV-store negotiation protocol (so fusion/autotune may stay enabled)."""
    if not _multi_controller():
        return False
    from horovod_tpu.core import coordinator as _coord

    if not _coord.negotiation_enabled():
        return False
    try:
        _coord.JaxKV()
        return True
    except _coord.KVError:
        return False


def record_cache_config(capacity: int, forced_off: bool = False):
    """Surface the EFFECTIVE negotiation-cache capacity in telemetry
    (`hvd.telemetry_report()` then says whether the cache is on, and
    whether the negotiation-fallback rule forced it off along with
    fusion)."""
    tele.REGISTRY.gauge("engine.negotiation.cache_capacity").set(
        int(capacity))
    # Always written (not only when 1): a later engine generation with
    # negotiation available must clear a stale forced-off marker, or the
    # report would say "capacity 1024" and "forced off" at once.
    tele.REGISTRY.gauge("engine.negotiation.cache_forced_off").set(
        1 if forced_off else 0)


def config_from_env(cycle_time_s: Optional[float],
                    fusion_threshold: Optional[int],
                    stall_warning_s: float):
    """Shared env-knob parsing for both engine implementations (reference:
    operations.cc:1732-1804). Returns (cycle_time_s, fusion_threshold,
    stall_warning_s, cache_capacity).

    The negotiation response cache follows the same fallback rule as
    fusion: HVD_NEGOTIATION=0 or no usable KV store forces it off —
    without negotiated rounds there is no control plane to cache."""
    if cycle_time_s is None:
        ms = os.environ.get("HVD_CYCLE_TIME") or os.environ.get(
            "HOROVOD_CYCLE_TIME")
        cycle_time_s = float(ms) / 1000.0 if ms else DEFAULT_CYCLE_TIME_S
    if fusion_threshold is None:
        b = os.environ.get("HVD_FUSION_THRESHOLD") or os.environ.get(
            "HOROVOD_FUSION_THRESHOLD")
        fusion_threshold = int(b) if b else DEFAULT_FUSION_THRESHOLD
    from horovod_tpu.core import coordinator as _coord

    cache_capacity = _coord.cache_capacity_from_env()
    if _multi_controller():
        if not _negotiated():
            fusion_threshold = 0
            forced = cache_capacity > 0
            cache_capacity = 0
            record_cache_config(0, forced_off=forced)
        else:
            if _coord.aggregation_enabled():
                # Gather-tree rounds republish full tables through p0's
                # digest by design — the Coordinator keeps the cache off,
                # and telemetry must say 0, not pretend it is on.
                cache_capacity = 0
            record_cache_config(cache_capacity)
    st = os.environ.get("HVD_STALL_CHECK_TIME") or os.environ.get(
        "HOROVOD_STALL_CHECK_TIME")
    if st:  # seconds; reference hardcodes 60 (operations.cc:253)
        stall_warning_s = float(st)
    if os.environ.get("HVD_STALL_CHECK_DISABLE") or os.environ.get(
            "HOROVOD_STALL_CHECK_DISABLE"):
        stall_warning_s = 0.0
    return cycle_time_s, fusion_threshold, stall_warning_s, cache_capacity


def record_submit(op: str, nbytes: int, queue_depth: int):
    """Submit-side telemetry shared by both engine implementations (the
    native engine enqueues through Python too; only execution-side
    counters need its stats C API). Counter names are the parity contract
    tests/test_telemetry.py pins across the two engines."""
    tele.REGISTRY.counter(f"engine.submitted.{op}").inc()
    tele.REGISTRY.counter("engine.submitted.bytes").inc(int(nbytes))
    tele.REGISTRY.histogram(
        "engine.tensor_bytes", tele.BYTES_BUCKETS).observe(int(nbytes))
    tele.REGISTRY.gauge("engine.queue_depth").set(queue_depth)


def record_submit_batch(op: str, sizes, queue_depth: Optional[int],
                        ring_full: int = 0, ring_spins: int = 0):
    """Submit-side telemetry for ONE batched submit of ``len(sizes)``
    requests — the whole batch folds into one pass over the registry
    (one ``inc(n)`` per counter, one :meth:`Histogram.observe_many`)
    instead of N per-tensor ``record_submit`` calls, so instrumentation
    does not hand back the lock round-trips the batched ABI removed.
    Shared by both engines (the native engine's ring pressure counters
    arrive through its stats sync instead — it passes no ring args; the
    python twin has no ring, so the pair stays 0 and merely pins the
    counter names into existence for cross-engine parity).
    ``queue_depth=None`` skips the gauge: the native engine's batched
    path must NOT read its pending count here — that takes the engine
    mutex (and folds the submit ring), re-locking the very fast path the
    ring exists to unlock; its periodic stats sync owns the gauge."""
    n = len(sizes)
    total = int(sum(sizes))
    tele.REGISTRY.counter(f"engine.submitted.{op}").inc(n)
    tele.REGISTRY.counter("engine.submitted.bytes").inc(total)
    tele.REGISTRY.counter("engine.submit.batched").inc(n)
    tele.REGISTRY.counter("engine.ring.full").inc(ring_full)
    tele.REGISTRY.counter("engine.ring.spins").inc(ring_spins)
    tele.REGISTRY.histogram(
        "engine.tensor_bytes",
        tele.BYTES_BUCKETS).observe_many([int(s) for s in sizes])
    if queue_depth is not None:
        tele.REGISTRY.gauge("engine.queue_depth").set(queue_depth)


def record_wire(executor):
    """Wire-byte telemetry after one executor call: engine.wire_bytes =
    bytes the mesh collective actually shipped (int8 payload + f32
    scales under a quantized policy, full width otherwise);
    engine.wire_bytes.compressed = the subset shipped under a policy.
    The native engine feeds the SAME counters through its stats C API
    (hvd_result.wire_bytes/wire_compressed -> hvd_engine_stats)."""
    wire = int(getattr(executor, "last_wire_bytes", 0))
    comp = int(getattr(executor, "last_wire_compressed", 0))
    if wire:
        tele.REGISTRY.counter("engine.wire_bytes").inc(wire)
    if comp:
        tele.REGISTRY.counter("engine.wire_bytes.compressed").inc(comp)
    # Per-tier split of the hierarchical two-phase route (zero on every
    # flat route): engine.wire_bytes.dcn is the quantized 1/L cross-tier
    # payload, engine.wire_bytes.ici the full-width intra-tier share.
    # The native engine feeds the SAME counters through its stats C API
    # (hvd_result.wire_dcn/wire_ici -> hvd_engine_stats).
    dcn = int(getattr(executor, "last_wire_bytes_dcn", 0))
    ici = int(getattr(executor, "last_wire_bytes_ici", 0))
    if dcn:
        tele.REGISTRY.counter("engine.wire_bytes.dcn").inc(dcn)
    if ici:
        tele.REGISTRY.counter("engine.wire_bytes.ici").inc(ici)


def record_cycle(elapsed_s: float):
    """One engine cycle that executed work (idle ticks are not counted —
    both engines apply the same rule, so the counts are comparable)."""
    tele.REGISTRY.counter("engine.cycles").inc()
    tele.REGISTRY.counter("engine.cycle_seconds_total").inc(elapsed_s)


def _phase_class(phase: str) -> str:
    """Collapse a deadline-attribution phase (QUEUE / NEGOTIATE_* /
    ALLREDUCE / ALLGATHER / BROADCAST) to its residency class."""
    if phase == tl.QUEUE:
        return "queue"
    if phase.startswith("NEGOTIATE"):
        return "negotiate"
    return "exec"


def record_phase(cls: str, seconds: float):
    """One phase-residency observation (queue / negotiate / memcpy /
    exec). Instrument names and bucket boundaries are the cross-engine
    parity contract: the C++ engine feeds the SAME histograms through
    ``hvd_engine_latency`` (hvdcheck rule ``parity-latency``). The
    memcpy class counts one observation per fusion-buffer copy pass
    that performs a real copy (pack on both engines; the native staging
    copy-out too — the python twin unpacks by view and observes no
    copy-out)."""
    tele.REGISTRY.histogram(
        "engine.phase.queue" if cls == "queue" else
        "engine.phase.negotiate" if cls == "negotiate" else
        "engine.phase.memcpy" if cls == "memcpy" else
        "engine.phase.exec").observe(seconds)


def record_complete_latency(op: str, latency_s: float,
                            margin_s: Optional[float] = None,
                            priority: Optional[int] = None):
    """End-to-end submit→complete latency of ONE engine collective, per
    op class, plus — when the request carried a deadline — the margin
    remaining at completion (clipped at 0: a deadline-fired entry that
    completes late reports zero margin), plus — when a priority class
    is given — the per-class serving-plane split
    (engine.latency.class.*) the overload acceptance gate reads. Same
    parity contract as :func:`record_phase`. The compiled/AOT hot path
    feeds nothing here (hvd.jax.jit collectives stay uninstrumented —
    the bench headline's standing rule)."""
    tele.REGISTRY.histogram(
        "engine.latency.allreduce" if op == "allreduce" else
        "engine.latency.allgather" if op == "allgather" else
        "engine.latency.broadcast").observe(latency_s)
    if priority is not None:
        tele.REGISTRY.histogram(
            "engine.latency.class.high" if priority == 0 else
            "engine.latency.class.low" if priority == 2 else
            "engine.latency.class.normal").observe(latency_s)
    if margin_s is not None:
        tele.REGISTRY.histogram("engine.deadline.margin").observe(
            max(float(margin_s), 0.0))


def record_admission_rejected(shed: bool = False):
    """One admission-plane rejection. ``shed`` says the deadline-aware
    fast-fail (remaining deadline < current p50 queue+negotiate
    latency) rejected the request, rather than a class budget. Counter
    names are the cross-engine parity contract — the native engine
    feeds the SAME counters through its stats C API
    (hvd_engine_stats.admission_rejected / admission_shed)."""
    tele.REGISTRY.counter(
        "engine.admission.shed" if shed
        else "engine.admission.rejected").inc()


def record_admission(inflight):
    """Per-class in-flight gauges (ordered like PRIORITY_CLASSES) — the
    saturation view /healthz, the doctor and the fleet console read.
    The native engine calls this from its stats sync with its
    ``admission_inflight_*`` stats fields."""
    tele.REGISTRY.gauge("engine.admission.inflight.high").set(
        int(inflight[0]))
    tele.REGISTRY.gauge("engine.admission.inflight.normal").set(
        int(inflight[1]))
    tele.REGISTRY.gauge("engine.admission.inflight.low").set(
        int(inflight[2]))


# Reserved name prefix of the synthetic submits the engine.admit burst
# fault injects — the injector skips its own names, so a burst can
# never recurse.
ADMIT_BURST_PREFIX = "_hvd.admit.burst."
_admit_burst_seq = 0


def admission_burst_inject(engine, name: str):
    """Fault site ``engine.admit`` (mode ``burst``, core/faultline.py):
    deterministically inject N synthetic LOW-priority 1-element
    allreduces ahead of this submit, so admission/shedding behavior is
    chaos-testable without the full load harness. Rejected synthetic
    submits are swallowed (saturation rejecting the burst IS the
    scenario under test); survivors carry a short deadline and are
    retired by a daemon waiter, so they cannot wedge a negotiated world
    where peers never announce them. Shared by both engines — called at
    the top of the single-submit path (batched submits bypass it, like
    the per-request shed check)."""
    global _admit_burst_seq
    if name.startswith(ADMIT_BURST_PREFIX):
        return
    burst = flt.engine_admit_burst()
    if not burst:
        return
    handles = []
    for _ in range(int(burst)):
        _admit_burst_seq += 1
        try:
            handles.append(engine.allreduce_async(
                f"{ADMIT_BURST_PREFIX}{os.getpid()}.{_admit_burst_seq}",
                np.zeros(1, np.float32), False, deadline_ms=10000.0,
                priority="low"))
        except EngineError:
            continue
    if handles:
        def _retire():
            for h in handles:
                try:
                    engine.synchronize(h)
                except EngineError:
                    pass

        threading.Thread(target=_retire, name="hvd-admit-burst",
                         daemon=True).start()


def build_admission_summary(queue_depth, inflight, inflight_bytes,
                            max_inflight, max_bytes):
    """The admission-state body BOTH engines hand to /healthz, the
    doctor snapshot and the fleet console: queue depth, per-class
    in-flight counts/bytes against their budgets, and which
    class+budget is tripped (saturated). Built with ``dict(keyword=...)``
    on purpose — dict literals in this module are swept by the
    span-args vocabulary lint (hvdcheck parity-span-args)."""
    classes = {}
    saturated = []
    tripped_first = None
    for i, cls in enumerate(PRIORITY_CLASSES):
        tripped = []
        if max_inflight[i] > 0 and inflight[i] >= max_inflight[i]:
            tripped.append("max_inflight")
        if max_bytes[i] > 0 and inflight_bytes[i] >= max_bytes[i]:
            tripped.append("max_bytes")
        classes[cls] = dict(inflight=int(inflight[i]),
                            inflight_bytes=int(inflight_bytes[i]),
                            max_inflight=int(max_inflight[i]),
                            max_bytes=int(max_bytes[i]),
                            tripped=tripped)
        if tripped:
            saturated.append(cls)
            if tripped_first is None:
                tripped_first = dict(cls=cls, budget=tripped[0])
    return dict(queue_depth=int(queue_depth), classes=classes,
                saturated=saturated, tripped=tripped_first)


def doctor_on_hang(reason, kind, table, rank):
    """Engage the cross-rank hang doctor (core/doctor.py) on a
    hang-class flight dump: publish this rank's inspect table on the
    fleet/KV plane and attempt an attributed verdict. Shared by both
    engine implementations; never raises — post-mortem reporting must
    not take the engine down. Returns the verdict dict or None."""
    try:
        from horovod_tpu.core import doctor as _doctor

        return _doctor.on_hang(reason, kind, table, rank)
    except Exception:
        LOG.debug("hang doctor failed", exc_info=True)
        return None


def make_autotuner(engine):
    """Shared autotuner construction (reference: HOROVOD_AUTOTUNE,
    operations.cc:1797-1804). Returns a ParameterManager or None. In
    multi-controller worlds tuning runs on process 0 only and propagates
    through the negotiation round params, mirroring the reference where
    rank 0 tunes and broadcasts (parameter_manager.cc:63-77,203-236);
    without negotiation it stays off. Failures are reported, not silently
    swallowed, and never take the engine down."""
    from horovod_tpu.tune import ParameterManager, autotune_enabled

    if not autotune_enabled():
        return None
    if _multi_controller():
        from horovod_tpu.common import topology as _topo

        if not _negotiated() or _topo.process_index() != 0:
            return None
    try:
        return ParameterManager(engine)
    except Exception as exc:
        LOG.warning("HVD_AUTOTUNE requested but the autotuner failed to "
                    "start (%s); continuing without autotuning", exc)
        return None


class Engine:
    def __init__(
        self,
        executor=None,
        cycle_time_s: Optional[float] = None,
        fusion_threshold: Optional[int] = None,
        stall_warning_s: float = STALL_WARNING_TIME_S,
        timeline: Optional[tl.Timeline] = None,
    ):
        (self.cycle_time_s, self.fusion_threshold, stall_warning_s,
         self.cache_capacity) = config_from_env(
            cycle_time_s, fusion_threshold, stall_warning_s)
        self.stall_warning_s = stall_warning_s or STALL_WARNING_TIME_S
        self.stall_check_disabled = stall_warning_s == 0.0
        self.executor = executor or JaxExecutor()
        # Per-engine buffer pool (core/bufferpool.py): submit snapshots,
        # fusion buffers and executor outputs ride reused slabs. Per
        # ENGINE, not process-wide, so elastic teardown can poison
        # exactly the dying engine's pool (abandon below).
        self.pool = bpool.BufferPool()
        if getattr(self.executor, "pool", None) is None:
            self.executor.pool = self.pool
        # Engine-wide default wire format (HVD_COMPRESSION); per-request
        # policies override it at submit. Fails fast on misspellings.
        self.wire_default = wire_policy_from_env()
        # Per-tier DCN default (HVD_COMPRESSION_DCN) for the
        # hierarchical two-phase route; inert without two-tier
        # structure. Mutually exclusive with a uniform wire policy on
        # any one request (check_wire_exclusive).
        self.wire_dcn_default = wire_dcn_policy_from_env()
        # Deadline/cancel/drain plane: the engine-wide default deadline
        # (HVD_COLLECTIVE_DEADLINE_S), the count of in-flight entries
        # carrying a deadline (the sweep's zero-cost short circuit), and
        # the quiesce reason once admission is closed.
        self.default_deadline_s = collective_deadline_from_env()
        self._deadline_count = 0
        self._quiesced: Optional[str] = None
        # Serving-plane admission control: the default priority class
        # (HVD_PRIORITY) and the per-class in-flight budgets
        # (HVD_ADMISSION_MAX_INFLIGHT / _MAX_BYTES with per-class
        # overrides; 0 = unlimited), plus the per-class accounting the
        # budgets are enforced against (guarded by self._lock).
        self.priority_default = priority_from_env()
        self.adm_max_inflight, self.adm_max_bytes = admission_from_env()
        self._adm_inflight = [0] * len(PRIORITY_CLASSES)
        self._adm_bytes = [0] * len(PRIORITY_CLASSES)
        self.timeline = timeline if timeline is not None else tl.from_env()
        if self.timeline.enabled:
            # Staging time feeds the WAIT_FOR_DATA spans; only measured
            # (it costs a device sync) while a timeline is recording.
            self.executor.measure_staging = True
        self._param_manager = make_autotuner(self)
        self._queue: "queue.Queue[_Entry]" = queue.Queue()
        self._handles: Dict[int, _Handle] = {}
        self._pending_names: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self._next_handle = 0
        self._shutdown = threading.Event()
        self._wake = threading.Event()  # enqueue cuts idle sleeps short
        # Submitting a deadline'd entry breaks the watchdog's (possibly
        # 12 s) idle sleep immediately — the tightened sweep tick alone
        # would only take effect on the NEXT wait. Shutdown sets it too.
        self._stall_kick = threading.Event()
        self._last_stall_warn = 0.0
        # Negotiated multi-controller path (core/coordinator.py): entries
        # drained but not yet agreed with the peer processes.
        self._coordinator = None
        self._coord_unavailable = False
        self._negotiating: list = []
        self._extra_wait = 0.0
        # Clock-anchor sync emitted into the timeline once the
        # coordinator's exchange completes (distributed tracing).
        self._clock_synced = False
        # Post-mortem hook: SIGUSR1 dumps the flight recorder of a live
        # (possibly hung) run — no env var needed.
        tl.install_sigusr1(self._dump_sigusr1)
        self._thread = threading.Thread(
            target=self._loop, name="hvd-background", daemon=True
        )
        self._thread.start()
        # Stall detection runs on its own watchdog thread: the dispatch
        # thread may itself be blocked inside a hung collective — exactly
        # the condition to report (reference rationale: operations.cc:
        # 1535-1581; there the check rides the coordinator tick).
        self._stall_thread = threading.Thread(
            target=self._stall_loop, name="hvd-stall-watchdog", daemon=True
        )
        self._stall_thread.start()

    # -- enqueue API (reference: EnqueueTensorAllreduce/Allgather/Broadcast,
    # operations.cc:2264-2380) ------------------------------------------------

    def _enqueue(self, entry: _Entry, mem_span=None) -> int:
        # Fault site engine.admit (burst mode): synthetic low-priority
        # submits land ahead of this one (a no-op without the fault).
        admission_burst_inject(self, entry.name)
        # Fault site engine.submit (core/faultline.py): a failed submit
        # raises before any handle/queue state exists — same observable
        # shape as an organic enqueue rejection.
        injected = flt.engine_submit(entry.name)
        if injected is not None:
            raise EngineError(injected)
        with self._lock:
            if self._shutdown.is_set():
                raise ShutdownError("engine is shut down")
            if self._quiesced is not None:
                # Admission closed (quiesce): fail FAST with a
                # descriptive error — new work must not ride into a
                # draining engine (graceful preemption, elastic shrink).
                raise EngineError(
                    f"engine is draining ({self._quiesced}): submissions "
                    "are closed — the engine is completing in-flight "
                    "work before shutdown (quiesce)")
            if entry.name in self._pending_names:
                raise DuplicateNameError(
                    f"a collective named '{entry.name}' is already pending; "
                    "names must be unique among in-flight tensors"
                )
            self._check_admission_locked(entry)
            h = _Handle(entry.name)
            entry.handle = self._next_handle
            self._next_handle += 1
            self._handles[entry.handle] = h
            self._pending_names[entry.name] = entry
            self._adm_inflight[entry.priority] += 1
            self._adm_bytes[entry.priority] += int(entry.tensor.nbytes)
            adm = list(self._adm_inflight)
            if entry.deadline is not None:
                self._deadline_count += 1
                self._stall_kick.set()
            depth = len(self._pending_names)
        record_admission(adm)
        record_submit(entry.op, entry.tensor.nbytes, depth)
        # Numerics (core/numerics.py): the local nonfinite count of the
        # SNAPSHOT is the attribution side of the synchronize-time check
        # — a poisoned reduced result names the submitting process.
        numx.engine_note_submit(entry.name, entry.tensor)
        if mem_span is not None:
            # The submit-time snapshot as a retro MEMCPY span at the head
            # of the QUEUE span; the END args carry the zero-copy
            # attribution ({"pooled": bool} / {"donated": true}) the
            # trace CLI splits copy-phase medians by.
            t0, t1, args = mem_span
            self.timeline.start(entry.name, tl.QUEUE, ts_us=t0)
            self.timeline.start(entry.name, tl.MEMCPY, ts_us=t0)
            self.timeline.end(entry.name, tl.MEMCPY, args, ts_us=t1)
        else:
            self.timeline.start(entry.name, tl.QUEUE)
        self._queue.put(entry)
        self._wake.set()
        return entry.handle

    def _check_admission_locked(self, entry: _Entry):
        """Admission control (the serving-plane subsystem): reject a
        submit SYNCHRONOUSLY when its priority class is at budget, and
        shed a deadline'd submit whose remaining margin is provably
        smaller than the current p50 queue+negotiate latency — instead
        of letting it rot in QUEUE past its deadline. Rejection happens
        at the submit boundary ONLY: never mid-flight, never tearing a
        fused batch (the cancel doctrine). Runs under the engine lock;
        raises :class:`AdmissionRejected`."""
        cls = entry.priority
        limit = self.adm_max_inflight[cls]
        blimit = self.adm_max_bytes[cls]
        nbytes = int(entry.tensor.nbytes)
        if limit > 0 and self._adm_inflight[cls] + 1 > limit:
            record_admission_rejected()
            raise AdmissionRejected(
                f"admission rejected for '{entry.name}' on "
                f"{_process_str()}: priority class "
                f"'{PRIORITY_NAMES[cls]}' is at its in-flight budget "
                f"({self._adm_inflight[cls]}/{limit} requests, "
                "HVD_ADMISSION_MAX_INFLIGHT); resubmit after in-flight "
                "work completes, or raise the budget")
        if blimit > 0 and self._adm_bytes[cls] + nbytes > blimit:
            record_admission_rejected()
            raise AdmissionRejected(
                f"admission rejected for '{entry.name}' on "
                f"{_process_str()}: priority class "
                f"'{PRIORITY_NAMES[cls]}' is at its bytes budget "
                f"({self._adm_bytes[cls]} in flight + {nbytes} > "
                f"{blimit} bytes, HVD_ADMISSION_MAX_BYTES); resubmit "
                "after in-flight work completes, or raise the budget")
        if entry.deadline is not None:
            est = queue_latency_estimate()
            if (est is not None
                    and entry.deadline - time.monotonic() < est):
                record_admission_rejected(shed=True)
                raise AdmissionRejected(
                    f"shed '{entry.name}' on {_process_str()}: its "
                    "remaining deadline is smaller than the current "
                    f"p50 queue+negotiate latency ({est * 1e3:.1f} ms) "
                    "— it would expire in QUEUE (deadline-aware "
                    "fast-fail; counted in engine.admission.shed)")

    # Submit-time SNAPSHOT (pool-slab copy — np.array before the pool):
    # the C++ engine memcpys at enqueue (hvdcore.cc), so a caller
    # mutating its buffer after an *_async call must not change what gets
    # reduced — the python twin owes the same observable semantics, and
    # frontends hand over zero-copy views (torch .numpy()/bf16
    # reinterpret). ``donate=True`` skips the copy: the engine takes
    # ownership and references the buffer in place (read-only — results
    # land in separate pool buffers), so the caller must not touch it
    # again; the numpy view is flagged unwriteable so an in-process
    # mutation raises rather than corrupting the reduction.
    def _snapshot(self, tensor, donate: bool):
        """(array, donated, flipped-read-only, (t0, t1, span_args))."""
        t0 = self.timeline.now_us()
        a = np.asarray(tensor)
        if donate and a.flags["C_CONTIGUOUS"]:
            flipped = _freeze_donated(a)
            return a, True, flipped, (t0, self.timeline.now_us(),
                                      {"donated": True})
        snap, tracked = self.pool.snapshot(a)
        return snap, False, False, (t0, self.timeline.now_us(),
                                    {"pooled": tracked})

    def _submit(self, entry: _Entry, span, flipped: bool) -> int:
        try:
            return self._enqueue(entry, span)
        except Exception:
            # Rejected submit: the engine never took ownership — a
            # donated buffer we froze must become writable again.
            if flipped:
                entry.tensor.flags.writeable = True
            raise

    def _abs_deadline(self, deadline_ms: Optional[float]) -> Optional[float]:
        """Per-request ``deadline_ms`` (overrides the engine-wide
        HVD_COLLECTIVE_DEADLINE_S default; <= 0 disables for this
        request) as an absolute monotonic instant, or None."""
        if deadline_ms is not None:
            return (time.monotonic() + deadline_ms / 1000.0
                    if deadline_ms > 0 else None)
        if self.default_deadline_s is not None:
            return time.monotonic() + self.default_deadline_s
        return None

    def _priority(self, priority, name: str) -> int:
        """Per-request priority class (None defers to HVD_PRIORITY)."""
        return (resolve_priority(priority, name)
                if priority is not None else self.priority_default)

    def allreduce_async(self, name: str, tensor: np.ndarray, average: bool,
                        prescale: float = 1.0,
                        compression: Optional[str] = None,
                        compression_dcn: Optional[str] = None,
                        donate: bool = False,
                        deadline_ms: Optional[float] = None,
                        priority: Optional[str] = None) -> int:
        # `compression` is the per-request engine wire policy (frontend
        # Compression objects carry it as .engine_wire); None defers to
        # the HVD_COMPRESSION default. `compression_dcn` is the per-TIER
        # policy of the hierarchical route (HVD_COMPRESSION_DCN default)
        # — mutually exclusive with a uniform wire policy.
        wire = (resolve_wire_policy(compression)
                if compression is not None else self.wire_default)
        wire_dcn = (resolve_wire_policy(compression_dcn)
                    if compression_dcn is not None
                    else self.wire_dcn_default)
        check_wire_exclusive(wire, wire_dcn, name)
        prio = self._priority(priority, name)
        snap, donated, flipped, span = self._snapshot(tensor, donate)
        return self._submit(
            _Entry(-1, name, "allreduce", snap, average=average,
                   prescale=prescale, compression=wire,
                   compression_dcn=wire_dcn, donated=donated,
                   deadline=self._abs_deadline(deadline_ms),
                   priority=prio),
            span, flipped)

    def allgather_async(self, name: str, tensor: np.ndarray,
                        donate: bool = False,
                        deadline_ms: Optional[float] = None,
                        priority: Optional[str] = None) -> int:
        prio = self._priority(priority, name)
        snap, donated, flipped, span = self._snapshot(tensor, donate)
        return self._submit(
            _Entry(-1, name, "allgather", snap, donated=donated,
                   deadline=self._abs_deadline(deadline_ms),
                   priority=prio),
            span, flipped)

    def broadcast_async(self, name: str, tensor: np.ndarray, root_rank: int,
                        donate: bool = False,
                        deadline_ms: Optional[float] = None,
                        priority: Optional[str] = None) -> int:
        prio = self._priority(priority, name)
        snap, donated, flipped, span = self._snapshot(tensor, donate)
        return self._submit(
            _Entry(-1, name, "broadcast", snap, root_rank=root_rank,
                   donated=donated,
                   deadline=self._abs_deadline(deadline_ms),
                   priority=prio),
            span, flipped)

    def submit_n(self, op: str, requests) -> List[int]:
        """Batched submit — the python twin of ``hvd_engine_enqueue_n``:
        one validation pass, one snapshot pass (name-bound pool slabs,
        :meth:`BufferPool.snapshot_bound`), ONE lock acquisition and one
        wakeup for N :class:`SubmitRequest` of a single collective op.
        Returns N handles in request order; per-request ``deadline_ms``
        / ``compression`` / ``donate`` are preserved.

        The duplicate-name contract is DEFERRED: a request whose name is
        already in flight does not fail the batch — that handle alone
        fails, and its ``synchronize`` raises
        :class:`DuplicateNameError`. (The C++ engine admits
        ring-published batches asynchronously on the loop thread, where
        a synchronous per-request verdict no longer exists; the python
        twin owes the same observable semantics.) Mixed-op batches,
        empty batches and intra-batch duplicate names are rejected
        synchronously — those are caller bugs, not races."""
        if op not in ("allreduce", "allgather", "broadcast"):
            raise EngineError(f"batched submit: unsupported op {op!r}")
        reqs = list(requests)
        n = len(reqs)
        if n == 0:
            raise EngineError("batched submit needs at least one request")
        seen = set()
        for r in reqs:
            if r.name in seen:
                raise DuplicateNameError(
                    f"a collective named '{r.name}' appears twice in one "
                    "batched submit; names must be unique among in-flight "
                    "tensors")
            seen.add(r.name)
        # Fault site engine.submit: checked ONCE per batch, before any
        # buffer is frozen or snapshotted — same observable shape as a
        # synchronous enqueue rejection.
        injected = flt.engine_submit(reqs[0].name)
        if injected is not None:
            raise EngineError(injected)
        # Wire-policy validation BEFORE any buffer is frozen or
        # snapshotted: a bad spelling (or a uniform+per-tier conflict)
        # must reject the batch while the engine still owns nothing —
        # donated buffers frozen mid-loop would otherwise stay
        # read-only after the raise.
        wires: List[tuple] = []
        for r in reqs:
            wire = ("none" if op != "allreduce"
                    else (resolve_wire_policy(r.compression)
                          if r.compression is not None
                          else self.wire_default))
            wire_dcn = ("none" if op != "allreduce"
                        else (resolve_wire_policy(r.compression_dcn)
                              if r.compression_dcn is not None
                              else self.wire_dcn_default))
            check_wire_exclusive(wire, wire_dcn, r.name)
            # Priority resolves here too — a bad spelling must reject
            # the batch before any buffer is frozen.
            wires.append((wire, wire_dcn,
                          self._priority(getattr(r, "priority", None),
                                         r.name)))
        entries: List[_Entry] = []
        spans = []
        flipped: List[np.ndarray] = []
        for r, (wire, wire_dcn, prio) in zip(reqs, wires):
            t0 = self.timeline.now_us()
            a = np.asarray(r.tensor)
            if r.donate and a.flags["C_CONTIGUOUS"]:
                if _freeze_donated(a):
                    flipped.append(a)
                snap, donated = a, True
                args = {"donated": True}
            else:
                snap, tracked = self.pool.snapshot_bound(r.name, a)
                donated = False
                args = {"pooled": tracked}
            args["batch_n"] = n
            spans.append((t0, self.timeline.now_us(), args))
            entries.append(_Entry(
                -1, r.name, op, snap, average=r.average,
                root_rank=r.root_rank, prescale=r.prescale,
                compression=wire, compression_dcn=wire_dcn, donated=donated,
                deadline=self._abs_deadline(r.deadline_ms), batch_n=n,
                priority=prio))
        dup_failed = []
        handles: List[int] = []
        with self._lock:
            if self._shutdown.is_set() or self._quiesced is not None:
                # Whole-batch rejection: the engine never took
                # ownership, so every buffer frozen above flips back.
                for a in flipped:
                    a.flags.writeable = True
                if self._shutdown.is_set():
                    raise ShutdownError("engine is shut down")
                raise EngineError(
                    f"engine is draining ({self._quiesced}): submissions "
                    "are closed — the engine is completing in-flight "
                    "work before shutdown (quiesce)")
            # Whole-batch admission pre-check, all-or-nothing: a
            # batched submit over budget rejects synchronously BEFORE
            # any handle exists — admission never tears a batch (the
            # per-request shed fast-fail stays single-submit-only; same
            # rule as the C++ EnqueueN pre-check).
            need_n = [0] * len(PRIORITY_CLASSES)
            need_b = [0] * len(PRIORITY_CLASSES)
            for e in entries:
                need_n[e.priority] += 1
                need_b[e.priority] += int(e.tensor.nbytes)
            for cls in range(len(PRIORITY_CLASSES)):
                limit = self.adm_max_inflight[cls]
                blimit = self.adm_max_bytes[cls]
                if ((limit > 0
                     and self._adm_inflight[cls] + need_n[cls] > limit)
                        or (blimit > 0
                            and self._adm_bytes[cls] + need_b[cls]
                            > blimit)):
                    for a in flipped:
                        a.flags.writeable = True
                    record_admission_rejected()
                    raise AdmissionRejected(
                        f"admission rejected for a batched submit of "
                        f"{n} on {_process_str()}: priority class "
                        f"'{PRIORITY_NAMES[cls]}' is over budget "
                        f"({self._adm_inflight[cls]} in flight + "
                        f"{need_n[cls]} requested, "
                        "HVD_ADMISSION_MAX_INFLIGHT / "
                        "HVD_ADMISSION_MAX_BYTES); the batch is "
                        "rejected whole — admission never tears a "
                        "fused batch")
            for e in entries:
                h = _Handle(e.name)
                e.handle = self._next_handle
                self._next_handle += 1
                self._handles[e.handle] = h
                handles.append(e.handle)
                if e.name in self._pending_names:
                    # Deferred duplicate: registered but never queued —
                    # completed inline below, after the lock.
                    dup_failed.append((e, h))
                    continue
                self._pending_names[e.name] = e
                self._adm_inflight[e.priority] += 1
                self._adm_bytes[e.priority] += int(e.tensor.nbytes)
                if e.deadline is not None:
                    self._deadline_count += 1
                    self._stall_kick.set()
            adm = list(self._adm_inflight)
            depth = len(self._pending_names)
        record_admission(adm)
        # All N requests count as submitted — the native engine cannot
        # know at submit which will dup-fail at its async fold, so the
        # python twin counts identically to keep the counters parable.
        record_submit_batch(op, [e.tensor.nbytes for e in entries], depth)
        for e, (t0, t1, args) in zip(entries, spans):
            self.timeline.start(e.name, tl.QUEUE, ts_us=t0)
            self.timeline.start(e.name, tl.MEMCPY, ts_us=t0)
            self.timeline.end(e.name, tl.MEMCPY, args, ts_us=t1)
        dup_names = {e.name for e, _ in dup_failed}
        queued = [e for e in entries if e.name not in dup_names]
        numx.engine_note_submit_batch([e.name for e in queued],
                                      [e.tensor for e in queued])
        for e in queued:
            self._queue.put(e)
        for e, h in dup_failed:
            self.timeline.end(e.name, tl.QUEUE,
                              {"batch_n": e.batch_n} if e.batch_n > 1
                              else None)
            tele.REGISTRY.counter("engine.errors").inc()
            e.tensor = _RETIRED
            h.error = DuplicateNameError(
                f"a collective named '{e.name}' is already pending; "
                "names must be unique among in-flight tensors")
            h.event.set()
        self._wake.set()
        return handles

    # -- deadline / cancel / drain plane --------------------------------------

    def cancel(self, handle: int) -> bool:
        """Cooperative cancel. Pre-announce entries retire locally at the
        next cycle without executing; entries already announced to peers
        (or executing) complete cross-rank and DISCARD their result —
        either way ``synchronize`` raises :class:`CancelledError`.
        Returns False when the handle is unknown or already complete."""
        with self._lock:
            h = self._handles.get(handle)
            if h is None or h.event.is_set():
                return False
            for e in self._pending_names.values():
                if e.handle == handle:
                    e.cancelled = True
                    break
            else:
                return False
        self._wake.set()  # retire promptly even on an idle engine
        return True

    def _sweep_deadlines(self):
        """Fail the waiter of every overdue entry with an attributed
        :class:`CollectiveTimeout` naming the phase it is stuck in, plus
        ONE flight dump per sweep. Runs on the loop thread each cycle
        (QUEUE/NEGOTIATE phases) and on the stall watchdog thread (an
        executor call the loop is wedged inside). Zero work when no
        in-flight entry carries a deadline."""
        if not self._deadline_count:
            return
        now = time.monotonic()
        expired = []
        with self._lock:
            for e in self._pending_names.values():
                if (e.deadline is not None and not e.fired
                        and now > e.deadline):
                    e.fired = True
                    expired.append(e)
        if not expired:
            return
        lines = []
        for e in expired:
            age = now - e.enqueued_at
            err = CollectiveTimeout(
                f"collective '{e.name}' exceeded its deadline after "
                f"{age:.2f}s stuck in phase {e.phase} on {_process_str()}"
                " (the request is abandoned; a late completion will be "
                "discarded)")
            tele.REGISTRY.counter("engine.deadline_exceeded").inc()
            self.timeline.instant(e.name, tl.DEADLINE_EXCEEDED,
                                  {"phase": e.phase,
                                   "age_s": round(age, 3)})
            with self._lock:
                h = self._handles.get(e.handle)
            if h is not None and not h.event.is_set():
                h.error = err
                h.event.set()
            lines.append(f"{e.name} (phase {e.phase}, {age:.2f}s)")
        self._dump_flight("collective deadline exceeded: "
                          + ", ".join(lines), kind="deadline")

    def _cull(self, entries):
        """Retire cancelled / deadline-fired entries that have NOT been
        announced to peers yet (local retirement is safe — no peer lists
        them); returns the survivors in order. Announced entries keep
        negotiating/executing and discard their result at completion."""
        live = []
        for e in entries:
            if e.cancelled:
                self._complete(e, None, None)  # -> CancelledError path
            elif e.fired:
                self._complete(e, None, CollectiveTimeout(
                    f"collective '{e.name}' exceeded its deadline in "
                    f"phase {e.phase}"))
            else:
                live.append(e)
        return live

    def quiesce(self, deadline_s: float,
                reason: str = "quiesce requested"):
        """Drain for a graceful exit: close admission (new submits fail
        fast; ``/healthz`` reports ``draining``), complete negotiated
        in-flight work, and report what was drained. Bounded by
        ``deadline_s`` — work wedged behind a dead peer cannot be
        completed, only reported. Reused by elastic shrink and the
        graceful-preemption ladder."""
        with self._lock:
            already = self._quiesced is not None
            if not already:
                self._quiesced = reason

        def _names():
            with self._lock:
                return list(self._pending_names)

        return quiesce_drain(reason, deadline_s, already, _names,
                             self._wake.set,
                             min(self.cycle_time_s, 0.01))

    # -- completion API (reference: handle_manager.cc + mpi_ops_v2.cc poll/
    # wait_and_clear:228-338) -------------------------------------------------

    def poll(self, handle: int) -> bool:
        with self._lock:
            h = self._handles.get(handle)
        if h is None:
            raise EngineError(f"unknown handle {handle}")
        return h.event.is_set()

    def synchronize(self, handle: int) -> np.ndarray:
        with self._lock:
            h = self._handles.get(handle)
        if h is None:
            raise EngineError(f"unknown handle {handle}")
        h.event.wait()
        with self._lock:
            self._handles.pop(handle, None)
        if h.error is not None:
            raise h.error
        # Numerics: a nonfinite reduced result fires the attributed
        # `nonfinite` verdict (and raises under HVD_NUMERICS=halt) —
        # same hook, counters and verdict shape as the native engine's.
        numx.engine_check_result(h.name, h.result)
        return h.result

    # -- background loop (reference: RunLoopOnce, operations.cc:1921-2172) ----

    def _loop(self):
        while not self._shutdown.is_set():
            start = time.monotonic()
            self._run_cycle()
            elapsed = time.monotonic() - start
            # idle-round backoff keeps all-quiet negotiation rounds from
            # hammering the coordination service (identical on every
            # process, so rounds stay in lockstep).
            sleep = self.cycle_time_s - elapsed + self._extra_wait
            self._extra_wait = 0.0
            if sleep > 0:
                self._wake.wait(sleep)
            self._wake.clear()
        # The loop may have built the coordinator after shutdown() checked
        # for one — publish the tombstone here too so peers never wait out
        # the full negotiation timeout on a cleanly exiting process.
        if self._coordinator is not None:
            self._coordinator.close()
        # Fail whatever is left (reference: operations.cc:1833-1848).
        self._drain_with_error(ShutdownError("Horovod engine has been shut down"))

    def _drain(self):
        out = []
        while True:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                return out

    def _drain_with_error(self, err: Exception):
        entries = self._drain()
        if entries:
            # Work died in the queue (shutdown with requests outstanding,
            # poisoned engine): leave a post-mortem trace of the last N
            # events alongside the error the callers will see.
            self._dump_flight(
                f"drained {len(entries)} pending entr"
                f"{'y' if len(entries) == 1 else 'ies'} with error: {err}")
        for e in entries:
            self._complete(e, None, err)

    def _dump_flight(self, reason: str, kind: Optional[str] = None):
        """Dump the flight recorder (+ telemetry snapshot) — called on
        stalls, failed negotiations, deadline expiries, shutdown-drained
        work and SIGUSR1. ``kind`` tags hang-class dumps ("stall",
        "deadline", "negotiation", "sigusr1"): those embed the per-entry
        inspect table, engage the cross-rank hang doctor
        (core/doctor.py) for an attributed verdict, and key the dump
        rate limit separately so a prior unrelated dump cannot suppress
        a hang post-mortem. Never raises: post-mortem reporting must not
        take the engine down."""
        table = None
        verdict = None
        if kind is not None:
            try:
                table = self.inspect()
            except Exception:
                table = None
            verdict = doctor_on_hang(reason, kind, table,
                                     self.timeline.rank)
        tl.dump_and_warn(self.timeline.recent(), reason,
                         self.timeline.rank, LOG, kind=kind,
                         inspect=table, verdict=verdict)

    def _dump_sigusr1(self, reason: str):
        """SIGUSR1 entry point: an on-demand live-hang post-mortem —
        the dump embeds the inspect table and engages the doctor."""
        self._dump_flight(reason, kind="sigusr1")

    # -- introspection (the hang doctor's raw table) --------------------------

    def inspect(self) -> List[dict]:
        """Full per-entry state of every in-flight tensor — the hang
        doctor's raw table, superseding the bare pending-name list.
        Record shape (``ENGINE_INSPECT_KEYS``) is the cross-engine
        parity contract with ``hvd_engine_inspect``; hvdcheck rule
        ``parity-doctor`` machine-diffs the two writers."""
        c = self._coordinator
        rnd = int(getattr(c, "round", 0)) if c is not None else 0
        now = time.monotonic()
        out = []
        with self._lock:
            for e in self._pending_names.values():
                out.append(dict(
                    name=e.name,
                    op=e.op,
                    phase=e.phase,
                    phase_age_us=int((now - e.phase_since) * 1e6),
                    bytes=int(e.tensor.nbytes),
                    dtype=str(e.tensor.dtype),
                    wire=e.compression,
                    batch_n=int(e.batch_n),
                    priority=PRIORITY_NAMES.get(e.priority, "normal"),
                    deadline_remaining_us=(
                        None if e.deadline is None
                        else int((e.deadline - now) * 1e6)),
                    round=rnd))
        return out

    def admission_summary(self) -> dict:
        """Queue depth + per-class admission state for /healthz, the
        doctor snapshot and the fleet console (shared shape with the
        native engine via :func:`build_admission_summary`)."""
        with self._lock:
            inflight = list(self._adm_inflight)
            nbytes = list(self._adm_bytes)
            depth = len(self._pending_names)
        return build_admission_summary(depth, inflight, nbytes,
                                       self.adm_max_inflight,
                                       self.adm_max_bytes)

    def set_params(self, cycle_time_s: Optional[float] = None,
                   fusion_threshold: Optional[int] = None):
        """Live parameter updates (the autotuner drives this). In a
        negotiated multi-controller world, process 0's values propagate to
        every process through the round params (coordinator.negotiate)."""
        if cycle_time_s is not None and cycle_time_s > 0:
            self.cycle_time_s = cycle_time_s
        if fusion_threshold is not None and fusion_threshold >= 0:
            # Without negotiation, the multi-controller invariant holds
            # even if topology came up after engine construction: fusion
            # stays off.
            self.fusion_threshold = 0 if (
                _multi_controller() and not _negotiated()
            ) else fusion_threshold
        if (self.cache_capacity and _multi_controller()
                and not _negotiated()):
            # The response cache follows fusion's fallback rule: no
            # negotiated rounds, nothing to cache.
            self.cache_capacity = 0
            record_cache_config(0, forced_off=True)
        if self._coordinator is not None:
            self._coordinator.cycle_time_s = self.cycle_time_s
            self._coordinator.fusion_threshold = self.fusion_threshold

    def current_params(self):
        """(cycle_time_s, fusion_threshold) — same surface as the native
        engine's readback."""
        return self.cycle_time_s, self.fusion_threshold

    def _maybe_build_coordinator(self):
        """Lazily stand up negotiation once topology is known (the engine
        may be constructed before hvd.init())."""
        if self._coordinator is not None or self._coord_unavailable:
            return
        if not _multi_controller():
            return
        from horovod_tpu.core import coordinator as coord

        # warn_stalls=False: this engine's own watchdog thread already
        # attributes stalls via coordinator.missing_processes — a second
        # warning from inside negotiate() would be a duplicate.
        self._coordinator = coord.make_coordinator(
            self.cycle_time_s, self.fusion_threshold,
            0.0 if self.stall_check_disabled else self.stall_warning_s,
            warn_stalls=False, cache_capacity=self.cache_capacity)
        if self._coordinator is None:
            # Fall back to the unfused, name-ordered local path for good
            # (the response cache rides the same rule: no rounds to
            # compress).
            self._coord_unavailable = True
            self.fusion_threshold = 0
            if self.cache_capacity:
                self.cache_capacity = 0
                record_cache_config(0, forced_off=True)

    def _negotiated_cycle(self, entries):
        """One negotiation round: agree on batch composition with every
        peer process, then execute exactly the agreed groups (the role of
        the reference's RunLoopOnce negotiation half,
        operations.cc:1921-2172)."""
        from horovod_tpu.core import coordinator as coord

        t_cycle = time.monotonic()
        entries = self._cull(entries)  # cancel/deadline BEFORE announce
        for e in entries:
            # Phase attribution reuses the span vocabulary (the C++
            # sweep spells the same literals — hvdcheck parity-spans).
            record_phase("queue", t_cycle - e.phase_since)
            e.phase = f"NEGOTIATE_{e.op.upper()}"
            e.phase_since = t_cycle
            self.timeline.start(e.name, f"NEGOTIATE_{e.op.upper()}")
        self._negotiating.extend(entries)
        c = self._coordinator
        now = time.monotonic()
        metas = [
            coord.RequestMeta(
                name=e.name, op=e.op, dtype=str(e.tensor.dtype),
                itemsize=e.tensor.dtype.itemsize,
                shape=tuple(e.tensor.shape), average=e.average,
                root_rank=e.root_rank, prescale=e.prescale,
                age_s=now - e.enqueued_at, nbytes=e.tensor.nbytes,
                compression=e.compression,
                compression_dcn=e.compression_dcn,
                priority=e.priority)
            for e in self._negotiating
        ]
        t_neg = time.monotonic()
        try:
            decision = c.negotiate(metas)
            tele.REGISTRY.histogram("engine.negotiation_s").observe(
                time.monotonic() - t_neg)
        except Exception as exc:
            # Both twins raise ShutdownError for every completion after a
            # peer shut down, not just the first batch (the shared
            # predicate rates post-poison re-raises by message text).
            msg = str(exc)
            shutdownish = coord.is_shutdownish(exc)
            err = ShutdownError(msg) if shutdownish else EngineError(msg)
            if not shutdownish:
                # A hung negotiation (timeout, KV failure) is exactly the
                # post-mortem the flight recorder exists for; a clean
                # peer/local shutdown is not. Dump BEFORE failing the
                # round's entries: the doctor diagnoses off the inspect
                # table, so the victims must still be in it (the native
                # twin dumps from the negotiator trampoline before the
                # C++ loop culls — same order).
                self._dump_flight(f"negotiation failed: {msg}",
                                  kind="negotiation")
            for e in self._negotiating:
                self.timeline.end(e.name, f"NEGOTIATE_{e.op.upper()}")
                self._complete(e, None, err)
            self._negotiating.clear()
            return
        if c.clock_ready and not self._clock_synced:
            # The anchor exchange completed: embed rank 0's clock bridge
            # (+ the measured KV round trip) in this rank's trace so the
            # merge tool can align every rank on one time base.
            self._clock_synced = True
            self.timeline.clock_sync(c.clock_offset_us, c.clock_rtt_us)
        self.cycle_time_s = decision.cycle_time_s or self.cycle_time_s
        if decision.fusion_threshold is not None:
            self.fusion_threshold = decision.fusion_threshold
        self._extra_wait = decision.idle_backoff_s
        if c.last_tables:
            # Per-process readiness instants inside the NEGOTIATE_* span
            # (reference: timeline.cc:106-130) — the trace names who was
            # late, not just that negotiation was long.
            for e in self._negotiating:
                for p, names in c.last_tables.items():
                    if p not in e.ready_marked and e.name in names:
                        e.ready_marked.add(p)
                        self.timeline.instant(e.name, tl.RANK_READY,
                                              {"process": p})
        done = set()
        executed_bytes = 0
        # `cached` on the span end: whether the round that RESOLVED this
        # tensor took the response-cache bitvector fast path — the trace
        # CLI attributes fast vs full rounds from it.
        neg_args = {"cached": decision.cached}
        for g in decision.groups:
            ents = [self._negotiating[i] for i in g.indices]
            done.update(g.indices)
            for e in ents:
                self.timeline.end(e.name, f"NEGOTIATE_{e.op.upper()}",
                                  neg_args)
            if g.error:
                for e in ents:
                    self._complete(e, None, EngineError(g.error))
                continue
            executed_bytes += sum(e.tensor.nbytes for e in ents)
            if ents[0].op == "allreduce":
                self._exec_allreduce_batch(ents)
            else:
                for e in ents:
                    self._exec_single(e)
        if done:
            self._negotiating = [e for i, e in enumerate(self._negotiating)
                                 if i not in done]
            record_cycle(time.monotonic() - t_cycle)
        if executed_bytes and self._param_manager is not None:
            self._param_manager.update(executed_bytes)

    def _run_cycle(self):
        t_cycle = time.monotonic()
        self._sweep_deadlines()
        entries = self._drain()
        self._maybe_build_coordinator()
        if self._coordinator is not None:
            self._negotiated_cycle(entries)
            return
        entries = self._cull(entries)  # cancelled/overdue: retire locally
        if len(entries) > 1:
            if _multi_controller():
                # Fallback (negotiation disabled/unavailable): sort each
                # drained cycle by (priority, name) so thread-racy
                # enqueue order within a cycle cannot diverge across
                # processes. Deadline margin is deliberately NOT in this
                # key — it is clock-local and would diverge. This is
                # per-cycle only — drain-boundary skew can still split a
                # batch differently on different processes, so this mode
                # requires a single enqueue thread with identical
                # program order (the negotiated path has no such
                # requirement).
                entries.sort(key=lambda e: (e.priority, e.name))
            else:
                # Single controller: drain in (priority, deadline
                # margin, name) order, so latency-sensitive serving
                # work overtakes bulk training traffic sharing the
                # cycle and tight deadlines run first within a class.
                now = time.monotonic()
                entries.sort(key=lambda e: (
                    e.priority,
                    e.deadline - now if e.deadline is not None
                    else float("inf"),
                    e.name))
        if entries and self._param_manager is not None:
            # One update per engine cycle with that cycle's traffic — the
            # manager's scoring window contract (parameter_manager.cc
            # scores bytes per cycle tick).
            self._param_manager.update(sum(e.tensor.nbytes for e in entries))
        if entries:
            # Fuse allreduces per (priority, dtype, average) in drain
            # order up to the threshold (reference: operations.cc:
            # 2035-2074); other ops run singly in order. Priority joins
            # the key so fused batches stay priority-uniform — a batch
            # is scheduled at its own class, never dragging high-class
            # work behind bulk traffic (or vice versa).
            batch: list[_Entry] = []
            batch_key = None
            batch_bytes = 0
            for e in entries:
                if e.op == "allreduce":
                    key = (e.priority, e.tensor.dtype, e.average,
                           e.compression, e.compression_dcn)
                    if batch and (key != batch_key or
                                  batch_bytes + e.tensor.nbytes > self.fusion_threshold):
                        self._exec_allreduce_batch(batch)
                        batch, batch_bytes = [], 0
                    batch_key = key
                    batch.append(e)
                    batch_bytes += e.tensor.nbytes
                else:
                    if batch:
                        self._exec_allreduce_batch(batch)
                        batch, batch_bytes = [], 0
                    self._exec_single(e)
            if batch:
                self._exec_allreduce_batch(batch)
            record_cycle(time.monotonic() - t_cycle)

    def _emit_exec_spans(self, entries, activity, t0_us):
        """Retro-emit WAIT_FOR_DATA (host→device staging, reference:
        operations.cc:783-807) + the op activity for one executor call.
        The executor measured its own staging time; the split point lands
        between the two spans."""
        t1 = self.timeline.now_us()
        stage_us = int(getattr(self.executor, "last_stage_s", 0.0) * 1e6)
        split = min(t0_us + stage_us, t1)
        for e in entries:
            args = {"dtype": str(e.tensor.dtype),
                    "shape": list(e.tensor.shape)}
            if e.compression not in ("", "none"):
                # Wire-policy attribution, matching the C++ writer's
                # TensorArgs (no arg at full width) — hvdcheck
                # parity-span-args pins the two vocabularies together.
                args["wire"] = e.compression
            if e.compression_dcn not in ("", "none"):
                # Per-tier DCN policy of the hierarchical route; same
                # parity contract as `wire` above.
                args["wire_dcn"] = e.compression_dcn
            if e.priority != PRIORITY_CODES["normal"]:
                # Serving-plane class attribution (no arg for the
                # default class, like the wire policies above).
                args["priority"] = PRIORITY_CLASSES[e.priority]
            self.timeline.start(e.name, tl.WAIT_FOR_DATA, ts_us=t0_us)
            self.timeline.end(e.name, tl.WAIT_FOR_DATA, ts_us=split)
            self.timeline.start(e.name, activity, args, ts_us=split)
            self.timeline.end(e.name, activity, ts_us=t1)

    def _exec_allreduce_batch(self, batch):
        names = [e.name for e in batch]
        fused = len(batch) > 1
        if fused:
            # Fusion-buffer occupancy accounting (reference analogue: the
            # 64 MB fusion buffer, operations.cc:2035-2074).
            tele.REGISTRY.counter("engine.fused.batches").inc()
            tele.REGISTRY.counter("engine.fused.tensors").inc(len(batch))
            tele.REGISTRY.counter("engine.fused.bytes").inc(
                sum(e.tensor.nbytes for e in batch))
        try:
            if fused:
                t_pack = time.monotonic()
                for n in names:
                    self.timeline.start(n, tl.MEMCPY_IN_FUSION_BUFFER)
                dtype = batch[0].tensor.dtype
                if any(e.prescale != 1.0 for e in batch) \
                        and dtype.kind not in "fc":
                    # Degenerate corner (non-unit prescale on an integer
                    # batch): preserve the historical float-promoting
                    # concatenation semantics instead of pooling.
                    flat = np.concatenate(
                        [(e.tensor.reshape(-1) * e.prescale
                          if e.prescale != 1.0 else e.tensor.reshape(-1))
                         for e in batch])
                    pooled_fusion = False
                else:
                    # Pool-checked-out fusion buffer, reused across
                    # cycles (the reference's persistent fusion buffer,
                    # operations.cc:2035-2074).
                    flat, pooled_fusion = self.pool.checkout_tracked(
                        sum(e.tensor.size for e in batch), dtype)
                    off = 0
                    for e in batch:
                        n_ = e.tensor.size
                        src = e.tensor.reshape(-1)
                        if e.prescale != 1.0:
                            np.multiply(src, e.prescale,
                                        out=flat[off: off + n_])
                        else:
                            flat[off: off + n_] = src
                        off += n_
                record_phase("memcpy", time.monotonic() - t_pack)
                pool_args = {"pooled": pooled_fusion}
                for n in names:
                    self.timeline.end(n, tl.MEMCPY_IN_FUSION_BUFFER,
                                      pool_args)
            else:
                flat = batch[0].tensor.reshape(-1)
                if batch[0].prescale != 1.0:
                    flat = flat * batch[0].prescale
            t0 = self.timeline.now_us()
            t_exec = time.monotonic()
            for e in batch:
                record_phase(_phase_class(e.phase), t_exec - e.phase_since)
                e.phase = tl.ALLREDUCE  # deadline attribution: executing
                e.phase_since = t_exec
            # Wire policy rides an executor attribute, not a parameter,
            # so custom test executors with the historical two-arg
            # signature keep working (batches are policy-uniform — the
            # fusion key and the coordinator's grouping include it).
            self.executor.wire_policy = batch[0].compression
            self.executor.wire_policy_dcn = batch[0].compression_dcn
            out = self.executor.allreduce(flat, batch[0].average)
            # Release the fusion input before any completion wakes a
            # waiter: the caller's next cycle must find the slab free
            # (unless a test executor returned the input aliased as
            # output, in which case `out` legitimately pins it).
            flat = None
            record_wire(self.executor)
            self._emit_exec_spans(batch, tl.ALLREDUCE, t0)
            off = 0
            for e in batch:
                n = e.tensor.size
                if fused:
                    self.timeline.start(e.name, tl.MEMCPY_OUT_FUSION_BUFFER)
                result = out[off: off + n].reshape(e.tensor.shape)
                if fused:
                    self.timeline.end(e.name, tl.MEMCPY_OUT_FUSION_BUFFER,
                                      pool_args)
                self._complete(e, result, None)
                off += n
        except Exception as exc:  # surfaced at synchronize()
            for e in batch:
                self._complete(e, None, EngineError(str(exc)))

    def _exec_single(self, e: _Entry):
        try:
            t0 = self.timeline.now_us()
            t_exec = time.monotonic()
            record_phase(_phase_class(e.phase), t_exec - e.phase_since)
            e.phase = e.op.upper()  # deadline attribution: executing
            e.phase_since = t_exec
            if e.op == "allgather":
                out = self.executor.allgather(e.tensor)
                record_wire(self.executor)
                self._emit_exec_spans([e], tl.ALLGATHER, t0)
            elif e.op == "broadcast":
                out = self.executor.broadcast(e.tensor, e.root_rank)
                record_wire(self.executor)
                self._emit_exec_spans([e], tl.BROADCAST, t0)
            else:
                raise EngineError(f"unknown op {e.op}")
            self._complete(e, out, None)
        except Exception as exc:
            self._complete(e, None, EngineError(str(exc)))

    def _complete(self, e: _Entry, result, err: Optional[Exception]):
        now = time.monotonic()
        record_phase(_phase_class(e.phase), now - e.phase_since)
        record_complete_latency(
            e.op, now - e.enqueued_at,
            None if e.deadline is None else e.deadline - now,
            e.priority)
        if e.cancelled and err is None:
            # Cooperative cancel: the result (if the entry executed —
            # post-agreement cancels complete cross-rank) is DISCARDED
            # and the waiter sees CancelledError. Span + counter are the
            # cross-engine parity surface (CANCELLED / engine.cancelled).
            self.timeline.start(e.name, tl.CANCELLED)
            self.timeline.end(e.name, tl.CANCELLED)
            tele.REGISTRY.counter("engine.cancelled").inc()
            result, err = None, CancelledError(
                f"collective '{e.name}' was cancelled (cooperative "
                "cancel; result discarded)")
        self.timeline.end(
            e.name, tl.QUEUE,
            {"batch_n": e.batch_n} if e.batch_n > 1 else None)
        with self._lock:
            self._pending_names.pop(e.name, None)
            if e.deadline is not None and self._deadline_count > 0:
                self._deadline_count -= 1
            if self._adm_inflight[e.priority] > 0:
                self._adm_inflight[e.priority] -= 1
            self._adm_bytes[e.priority] = max(
                0, self._adm_bytes[e.priority] - int(e.tensor.nbytes))
            adm = list(self._adm_inflight)
            depth = len(self._pending_names)
            h = self._handles.get(e.handle)
        tele.REGISTRY.counter(
            "engine.errors" if err is not None else "engine.completed").inc()
        tele.REGISTRY.gauge("engine.queue_depth").set(depth)
        record_admission(adm)
        # Release the snapshot slab BEFORE waking the waiter: the cycle
        # loop's local batch list is the last engine-side reference, and
        # a submit-then-wait caller's next enqueue must find the slab
        # free, not race the loop thread for it.
        e.tensor = _RETIRED
        if h is not None and not h.event.is_set():
            # A deadline-fired handle was already released with its
            # attributed CollectiveTimeout — a late completion (the
            # wedged executor finally returning) must not clobber it.
            h.result = result
            h.error = err
            h.event.set()

    def _stall_loop(self):
        interval = max(self.stall_warning_s / 5.0, 0.01)
        while not self._shutdown.is_set():
            # Deadline enforcement for entries the LOOP thread cannot
            # reach (wedged inside an executor call): tighten the tick
            # while any in-flight entry carries a deadline, so an
            # exec-stuck collective fails its waiter promptly and not on
            # the (much coarser) stall-warning cadence. The kick breaks
            # an already-started coarse sleep the moment a deadline'd
            # entry is submitted.
            tick = min(interval, 0.05) if self._deadline_count else interval
            if self._stall_kick.wait(tick):
                self._stall_kick.clear()
            if self._shutdown.is_set():
                return
            self._sweep_deadlines()
            self._check_stalls()

    def _check_stalls(self):
        """Warn about tensors stuck in the table (reference:
        CheckForStalledTensors, operations.cc:1535-1581)."""
        if self.stall_check_disabled:
            return
        now = time.monotonic()
        if now - self._last_stall_warn < self.stall_warning_s:
            return
        with self._lock:
            stalled = [
                (n, now - e.enqueued_at)
                for n, e in self._pending_names.items()
                if now - e.enqueued_at > self.stall_warning_s
            ]
        if stalled:
            self._last_stall_warn = now
            c = self._coordinator

            def _fmt(n, age):
                # Name the processes holding this tensor up (reference:
                # CheckForStalledTensors, operations.cc:1535-1581).
                if c is not None and c.last_tables:
                    missing = c.missing_processes(n)
                    if missing:
                        from horovod_tpu.core import coordinator as coord

                        line = (f"{n} ({int(age)}s; missing from "
                                f"process(es): "
                                f"{', '.join(map(str, missing))})")
                        # Unresolvable-divergence diagnosis (same family,
                        # different sequence number on a peer).
                        return line + (coord.divergence_hint(c, n) or "")
                return f"{n} ({int(age)}s)"

            names = ", ".join(_fmt(n, age) for n, age in stalled)
            if c is not None and c.waiting_on is not None:
                names += (f" [negotiation is blocked waiting for process "
                          f"{c.waiting_on}]")
            # Same registry the straggler report reads: name the rank
            # with the largest cumulative imposed wait so far.
            worst = tele.STRAGGLERS.worst_line()
            if worst:
                names += " " + worst
            LOG.warning(
                "One or more tensors were submitted to be reduced/gathered/"
                "broadcast but have not completed for over %ds: %s",
                int(self.stall_warning_s), names,
            )
            # Post-mortem: the stalled world's last N events + telemetry,
            # dumped while the dispatch thread may itself be hung.
            self._dump_flight(f"stalled tensors: {names}", kind="stall")
            # The performance sentinel folds the stall into /healthz and
            # into the next watchdog verdict's attribution.
            try:
                from horovod_tpu.core import sentinel as _sentinel

                _sentinel.note_stall(f"stalled tensors: {names}",
                                     self.timeline.rank)
            except Exception:
                pass

    def abandon(self):
        """Elastic teardown of a WEDGED engine (core/elastic.py): the
        coordination KV host died and blocked KV RPCs never return, so
        :meth:`shutdown`'s thread join would hang forever. Fail the
        outstanding handles, poison the coordinator WITHOUT publishing
        (a tombstone set would wedge too), and leave the loop thread
        parked inside the dead service — the caller parks this object
        so nothing it references is ever destroyed."""
        c = self._coordinator
        if c is not None:
            c.dead = c.dead or "engine abandoned (elastic reconfiguration)"
            c._closed = True  # a blocked read aborts IF it ever returns
        # Pool hygiene: the parked loop thread may still hold checked-out
        # slabs (it is wedged inside the dead backend) — poison the pool
        # so none of them can ever be handed to a later checkout. The
        # successor engine builds a fresh pool.
        self.pool.poison()
        self._shutdown.set()
        self._wake.set()
        self._stall_kick.set()
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
            self._pending_names.clear()
            self._adm_inflight = [0] * len(PRIORITY_CLASSES)
            self._adm_bytes = [0] * len(PRIORITY_CLASSES)
        for h in handles:
            if not h.event.is_set():
                h.error = ShutdownError(
                    "engine abandoned: coordination KV plane lost")
                h.event.set()
        self.timeline.close()
        tl.uninstall_sigusr1(self._dump_sigusr1)

    def shutdown(self):
        # Publish the shutdown tombstone first: peers blocked mid-round on
        # our next message discover it and surface ShutdownError instead
        # of hanging (reference: shutdown propagation via the coordinator,
        # operations.cc:2008-2011).
        if self._coordinator is not None:
            self._coordinator.close()
        self._shutdown.set()
        self._wake.set()  # break an idle sleep immediately
        self._stall_kick.set()
        self._thread.join(timeout=5)
        # If the loop thread was inside _maybe_build_coordinator when the
        # check above ran, the coordinator exists only now. Close it again:
        # a blocked negotiate() aborts at its next poll slice once _closed
        # is set (close() is idempotent), and the tombstone is published.
        if self._coordinator is not None:
            self._coordinator.close()
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
            self._pending_names.clear()
            self._adm_inflight = [0] * len(PRIORITY_CLASSES)
            self._adm_bytes = [0] * len(PRIORITY_CLASSES)
        for h in handles:
            if not h.event.is_set():
                h.error = ShutdownError("Horovod engine has been shut down")
                h.event.set()
        self.timeline.close()
        # A later SIGUSR1 must dump a LIVE engine's ring, not this dead
        # one's — and the module-global handler state must not pin us.
        tl.uninstall_sigusr1(self._dump_sigusr1)


_engine: Optional[Engine] = None
_engine_lock = threading.Lock()


def _make_engine():
    """HVD_ENGINE selects the implementation: 'native' (default — the C++
    libhvdcore scheduler) or 'python' (this module's reference engine).
    Falls back to Python if the native build is unavailable."""
    choice = os.environ.get("HVD_ENGINE", "native").lower()
    if choice == "native":
        try:
            from horovod_tpu.core.native_engine import NativeEngine

            return NativeEngine()
        except Exception as exc:  # no toolchain — degrade, loudly
            LOG.warning("native engine unavailable (%s); "
                        "falling back to the python engine", exc)
    return Engine()


def get_engine():
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = _make_engine()
        return _engine


def shutdown_engine():
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.shutdown()
            _engine = None


def quiesce_drain(reason: str, deadline_s: float, already: bool,
                  pending_names, wake, tick_s: float):
    """The quiesce policy BOTH engines share (core/native_engine.py
    calls this too): mark the process draining, bounded-drain until the
    in-flight table empties, and report NAMES. The report shape — name
    lists, not counts — and the draining marker/gauge/log wording are
    part of the engines' same-observable-semantics contract, so they
    live in exactly one place. ``pending_names`` is each engine's view
    of its in-flight table; ``wake`` nudges an idle loop (a no-op for
    the C++ engine, whose loop ticks on its own)."""

    def _shed_level() -> int:
        # Work leaving the table WITHOUT completing (deadline expiry,
        # cooperative cancel, admission shed) — sampled before/after
        # the drain window so the report splits shed from drained.
        # flat_counters() runs the registry syncs, so the native
        # engine's stats fold in before each sample.
        flat = tele.REGISTRY.flat_counters()
        return int(flat.get("engine.deadline_exceeded", 0)
                   + flat.get("engine.cancelled", 0)
                   + flat.get("engine.admission.shed", 0))

    shed0 = _shed_level()
    before = pending_names()
    tele.REGISTRY.gauge("engine.draining").set(1)
    try:
        from horovod_tpu.core import sentinel as _sentinel

        _sentinel.note_draining(reason)
    except Exception:
        pass
    deadline = time.monotonic() + max(0.0, deadline_s)
    pending = before
    while pending and time.monotonic() < deadline:
        wake()
        time.sleep(tick_s)
        pending = pending_names()
    drained = [n for n in before if n not in pending]
    report = dict(reason=reason, drained=drained,
                  still_pending=pending,
                  deadline_hit=bool(pending), already=already,
                  shed=max(0, _shed_level() - shed0))
    if pending:
        LOG.warning(
            "engine quiesce: drained %d of %d in-flight collective(s)"
            " within %.1fs; still pending: %s", len(drained),
            len(before), deadline_s, ", ".join(pending))
    else:
        LOG.info("engine quiesce: drained %d in-flight collective(s);"
                 " admission closed (%s)", len(drained), reason)
    return report


def quiesce_engine(deadline_s: float,
                   reason: str = "quiesce requested"):
    """Quiesce the engine singleton if one exists: close admission,
    drain in-flight work within ``deadline_s``, report what drained.
    Returns the report dict, or None when no engine was ever built.
    Reused by elastic shrink (a bounded politeness drain before the
    teardown) and the graceful-preemption ladder."""
    with _engine_lock:
        e = _engine
    if e is None:
        return None
    try:
        return e.quiesce(deadline_s, reason=reason)
    except Exception:
        LOG.warning("engine quiesce failed", exc_info=True)
        return None


def admission_summary():
    """Admission/saturation snapshot of the engine singleton, or None
    when no engine was ever built — the /healthz serving-plane body
    (queue depth, per-class in-flight vs budgets, tripped class)."""
    with _engine_lock:
        e = _engine
    if e is None:
        return None
    try:
        return e.admission_summary()
    except Exception:
        LOG.debug("admission summary failed", exc_info=True)
        return None


def abandon_engine():
    """Drop the engine singleton WITHOUT joining its threads — for
    elastic reconfiguration after the coordination KV plane died, where
    a blocked negotiation RPC never returns and a normal shutdown would
    hang on the join. Returns the abandoned engine so the caller can
    PARK it (its trampolines/threads must outlive the abandonment), or
    None when no engine existed."""
    global _engine
    with _engine_lock:
        e, _engine = _engine, None
    if e is None:
        return None
    try:
        e.abandon()
    except Exception:
        LOG.warning("engine abandon failed", exc_info=True)
    return e
