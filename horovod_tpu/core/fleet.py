"""Fleet observability plane: cross-rank telemetry rollups over the KV
plane, and the rank-0 world aggregator behind ``hvd.fleet_report()``.

Every observability surface before this one is per-process — N ranks
means N ``/metrics`` endpoints and no answer to "what is the world's
p99 allreduce latency right now?". This module closes that gap without
inventing a transport: each process periodically publishes a compact
telemetry snapshot to the existing KV plane (``FileKV`` under the fleet
directory — atomic rename, so readers never see a torn value; PR 11's
durability rule), and rank 0 merges the per-rank snapshots into world
rollups:

- global per-op latency quantiles (p50/p99/p999) — histograms merge
  EXACTLY because both engines feed identical bucket edges
  (``LATENCY_BUCKETS_S``, machine-checked by hvdcheck rule
  ``parity-latency``): merging is just summing count arrays;
- per-rank imbalance/straggler heatmap (queue depth, step time, beat
  age), world gauges (min/mean/max spreads);
- liveness: a rank whose snapshot sequence number stops advancing for
  ``HVD_FLEET_LEASE_S`` is marked STALE (judged by the READER's clock —
  same rule as the elastic heartbeat lease); a rank in the elastic
  death-note plane is DEAD. Neither ever blocks the aggregator — a dead
  peer must not wedge the rollup.

Surfaces: ``hvd.fleet_report()`` (dict), the ``/fleet`` arm on the
rank-0 telemetry endpoint, per-rank-labeled Prometheus series appended
to rank 0's ``/metrics``, and the live console
``python -m horovod_tpu.utils.stats --fleet <target> [--watch]``.

The publisher is OFF by default: it starts from ``topology.init`` only
when a fleet directory resolves (``HVD_FLEET_DIR``, or
``<HVD_ELASTIC_DIR>/fleet`` when the elastic plane is up) and
``HVD_FLEET`` is not ``0``. ``bench.py`` sets neither, so the headline
path never pays for this plane. The compiled/AOT hot path is untouched
either way — snapshots read the registry, they never instrument the
step.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

LOG = logging.getLogger("horovod_tpu.fleet")

# The histogram vocabulary that rides every snapshot (the cross-engine
# latency instruments; hvdcheck pins both engines to these names).
LATENCY_PREFIXES = ("engine.latency.", "engine.phase.", "engine.deadline.")

# The step-time ring for the console sparkline.
STEP_RING = "trainer.step_s"

_OPS = ("allreduce", "allgather", "broadcast")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def enabled() -> bool:
    return os.environ.get("HVD_FLEET", "1").lower() not in (
        "0", "false", "off")


def interval_s() -> float:
    """Publish cadence (seconds between snapshots)."""
    return max(0.1, _env_float("HVD_FLEET_INTERVAL_S", 2.0))


def fleet_lease_s() -> float:
    """Reader-clock lease: a rank whose snapshot seq is frozen this long
    is STALE. Defaults to three publish intervals so one missed tick
    (GC pause, loaded host) does not flap the marking."""
    return _env_float("HVD_FLEET_LEASE_S", 3.0 * interval_s())


def fleet_dir() -> Optional[str]:
    """Where snapshots live: ``HVD_FLEET_DIR``, or the elastic plane's
    shared directory when one exists (the supervisor already assumes
    shared storage there). None = plane off."""
    explicit = os.environ.get("HVD_FLEET_DIR")
    if explicit:
        return explicit
    try:
        from horovod_tpu.core import elastic

        d = elastic.elastic_dir()
    except Exception:  # pragma: no cover - defensive
        d = None
    return os.path.join(d, "fleet") if d else None


def _world_coords() -> Tuple[int, int]:
    """(generation, epoch) for key scoping — from the elastic world when
    it is up (epoch advances on every shrink/regrow, so a new world
    never merges against stale-epoch snapshots), else (HVD generation
    env, 0)."""
    try:
        from horovod_tpu.core import elastic

        summary = elastic.world_summary()
        if summary is not None:
            return int(summary["generation"]), int(summary["epoch"])
        return elastic.generation(), 0
    except Exception:  # pragma: no cover - defensive
        return 0, 0


def snapshot_key(generation: int, epoch: int, rank: int) -> str:
    return f"hvd/fleet/g{generation}/e{epoch}/p{rank}"


# ---------------------------------------------------------------------------
# Per-rank snapshot
# ---------------------------------------------------------------------------

def local_snapshot(rank: Optional[int] = None, seq: int = 0,
                   generation: Optional[int] = None,
                   epoch: Optional[int] = None) -> dict:
    """The compact per-rank telemetry snapshot the publisher ships:
    counters/gauges flat, the latency-vocabulary histograms as raw
    bucket counts (mergeable exactly), the step-time ring window, and
    the watchdog/numerics verdict summary."""
    from horovod_tpu.core import telemetry as tele

    if rank is None:
        try:
            from horovod_tpu.common import topology as topo

            rank = topo.process_index() if topo.is_initialized() else 0
        except Exception:  # pragma: no cover - defensive
            rank = 0
    if generation is None or epoch is None:
        g, e = _world_coords()
        generation = g if generation is None else generation
        epoch = e if epoch is None else epoch
    hists = {name: {"counts": h["counts"], "sum": h["sum"],
                    "count": h["count"]}
             for name, h in tele.REGISTRY.histogram_counts().items()
             if name.startswith(LATENCY_PREFIXES)}
    rings = {name: vals for name, vals
             in tele.REGISTRY.ring_values().items() if name == STEP_RING}
    health = None
    numerics = None
    try:
        from horovod_tpu.core import sentinel

        h = sentinel.health()
        health = h.get("status")
        numerics = (h.get("numerics") or {}).get("verdicts")
    except Exception:  # pragma: no cover - defensive
        pass
    # Serving-plane admission state (core/engine.py admission_summary,
    # one shape for both engines): queue depth + per-class in-flight vs
    # budget — the fleet console's saturation view rides the snapshot.
    admission = None
    try:
        from horovod_tpu.core import engine as _eng

        admission = _eng.admission_summary()
    except Exception:  # pragma: no cover - defensive
        pass
    # The hang doctor's latest attributed blame (core/doctor.py), in
    # compact form: the fleet console's blamed-tensor line rides the
    # ordinary snapshot plane — no extra keys, no extra reads.
    doctor = None
    try:
        from horovod_tpu.core import doctor as _doc

        v = _doc.last_verdict()
        if v and v.get("kind"):
            doctor = {"kind": v["kind"], "tensor": v.get("tensor"),
                      "ranks": v.get("ranks"),
                      "wall_us": v.get("wall_us")}
    except Exception:  # pragma: no cover - defensive
        pass
    return {
        "v": 1,
        "rank": int(rank),
        "seq": int(seq),
        "wall": time.time(),
        "generation": int(generation),
        "epoch": int(epoch),
        "counters": dict(tele.REGISTRY.flat_counters()),
        "gauges": dict(tele.REGISTRY.flat_gauges()),
        "hists": hists,
        "rings": rings,
        "health": health,
        "numerics": numerics,
        "admission": admission,
        "doctor": doctor,
    }


class FleetPublisher:
    """Background thread: one compact snapshot to the KV plane per
    interval, epoch-scoped keys, rename-only durability (durable=False —
    a beat lost to power failure is indistinguishable from a missed
    tick, and the control loop must not fsync per tick)."""

    def __init__(self, kv, rank: int,
                 interval: Optional[float] = None):
        self._kv = kv
        self._rank = rank
        self._interval = interval_s() if interval is None else interval
        self._seq = 0
        self._last_key: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self):
        """One snapshot to the current (generation, epoch) key. Epoch
        rollover (elastic shrink) retires the previous key so dead
        epochs do not accumulate in the plane."""
        g, e = _world_coords()
        self._seq += 1
        snap = local_snapshot(rank=self._rank, seq=self._seq,
                              generation=g, epoch=e)
        key = snapshot_key(g, e, self._rank)
        if self._last_key is not None and self._last_key != key:
            try:
                self._kv.delete(self._last_key)
            except Exception:  # pragma: no cover - defensive
                pass
        try:
            self._kv.set(key, json.dumps(snap), durable=False)
        except TypeError:
            # KV backends without the durability knob (LocalKV in unit
            # tests) take the plain two-argument form.
            self._kv.set(key, json.dumps(snap))
        self._last_key = key

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self.publish_once()
            except Exception:  # publishing must never kill the thread
                LOG.debug("fleet publish failed", exc_info=True)

    def start(self):
        if self._thread is not None:
            return
        try:
            self.publish_once()  # first beat now, not one interval late
        except Exception:  # pragma: no cover - defensive
            LOG.debug("fleet first publish failed", exc_info=True)
        self._thread = threading.Thread(
            target=self._loop, name="hvd-fleet-publish", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None


# ---------------------------------------------------------------------------
# Rank-0 aggregation
# ---------------------------------------------------------------------------

def _quantiles_us(bounds: List[float], counts: List[int]) -> dict:
    from horovod_tpu.core import telemetry as tele

    out = {}
    for label, q in (("p50", 0.50), ("p99", 0.99), ("p999", 0.999)):
        v = tele.quantile_from_buckets(bounds, counts, q)
        out[f"{label}_us"] = None if v is None else round(v * 1e6, 1)
    return out


def merge_snapshots(snaps: List[dict],
                    states: Optional[Dict[int, str]] = None) -> dict:
    """Merge per-rank snapshots into the world rollup. Histograms merge
    exactly (identical bucket edges on every rank — summed counts);
    counters sum; gauges report min/mean/max spreads plus the per-rank
    heatmap. ``states`` overrides the liveness marking per rank (the
    aggregator's lease/death verdicts); ranks default to OK."""
    from horovod_tpu.core import telemetry as tele

    bounds = list(tele.LATENCY_BUCKETS_S)
    now = time.time()
    states = states or {}

    ranks: Dict[int, dict] = {}
    counters: Dict[str, float] = {}
    gauges_per_rank: Dict[str, Dict[int, float]] = {}
    hists: Dict[str, dict] = {}
    step_last: Dict[int, Optional[float]] = {}
    sparkline: List[float] = []
    doctor: Optional[dict] = None
    saturated_ranks: Dict[int, List[str]] = {}
    generation = epoch = 0
    for snap in snaps:
        rank = int(snap["rank"])
        generation = max(generation, int(snap.get("generation", 0)))
        epoch = max(epoch, int(snap.get("epoch", 0)))
        ring = (snap.get("rings") or {}).get(STEP_RING) or []
        step_last[rank] = ring[-1] if ring else None
        if ring and len(ring) > len(sparkline):
            sparkline = list(ring)
        ranks[rank] = {
            "seq": snap.get("seq"),
            "age_s": round(max(0.0, now - snap.get("wall", now)), 3),
            "state": states.get(rank, "OK"),
            "health": snap.get("health"),
            "numerics": snap.get("numerics"),
            "queue_depth": (snap.get("gauges") or {}).get(
                "engine.queue_depth"),
            "pool_bytes": (snap.get("gauges") or {}).get(
                "engine.pool.bytes_resident"),
            "step_s": step_last[rank],
            "saturated": sorted((snap.get("admission") or {}).get(
                "saturated") or []),
        }
        if ranks[rank]["saturated"]:
            saturated_ranks[rank] = ranks[rank]["saturated"]
        blame = snap.get("doctor")
        if blame and blame.get("kind") and (
                doctor is None
                or (blame.get("wall_us") or 0)
                > (doctor.get("wall_us") or 0)):
            doctor = blame  # newest attributed hang blame wins
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in (snap.get("gauges") or {}).items():
            gauges_per_rank.setdefault(name, {})[rank] = v
        for name, h in (snap.get("hists") or {}).items():
            agg = hists.setdefault(
                name, {"counts": [0] * (len(bounds) + 1),
                       "sum": 0.0, "count": 0})
            counts = h.get("counts") or []
            if len(counts) != len(agg["counts"]):
                continue  # foreign bucket layout: never corrupt the merge
            agg["counts"] = [a + c for a, c in zip(agg["counts"], counts)]
            agg["sum"] += h.get("sum", 0.0)
            agg["count"] += h.get("count", 0)

    ops = {}
    for op in _OPS:
        h = hists.get(f"engine.latency.{op}")
        if h and h["count"]:
            ops[op] = dict(count=h["count"], **_quantiles_us(
                bounds, h["counts"]))
    phases = {}
    for name, h in sorted(hists.items()):
        if name.startswith("engine.phase.") and h["count"]:
            phases[name.split(".")[-1]] = dict(
                count=h["count"], **_quantiles_us(bounds, h["counts"]))
    # Per-priority-class completion latency (the serving-plane SLO
    # view): merged exactly like the per-op histograms above.
    classes = {}
    for cls in ("high", "normal", "low"):
        h = hists.get(f"engine.latency.class.{cls}")
        if h and h["count"]:
            classes[cls] = dict(count=h["count"], **_quantiles_us(
                bounds, h["counts"]))
    margin = hists.get("engine.deadline.margin")

    gauges = {}
    for name, per_rank in sorted(gauges_per_rank.items()):
        vals = list(per_rank.values())
        gauges[name] = {
            "min": min(vals), "max": max(vals),
            "mean": sum(vals) / len(vals),
            "per_rank": {str(r): v for r, v in sorted(per_rank.items())},
        }

    return {
        "v": 1,
        "wall": now,
        "generation": generation,
        "epoch": epoch,
        "size": len(ranks),
        "stale": sorted(r for r, s in states.items() if s == "STALE"),
        "dead": sorted(r for r, s in states.items() if s == "DEAD"),
        "ranks": {str(r): info for r, info in sorted(ranks.items())},
        "ops": ops,
        "phases": phases,
        "classes": classes,
        "deadline": {
            "margin_p50_s": (
                None if not (margin and margin["count"]) else round(
                    tele.quantile_from_buckets(
                        bounds, margin["counts"], 0.5), 6)),
            "exceeded": counters.get("engine.deadline_exceeded", 0),
            "cancelled": counters.get("engine.cancelled", 0),
            "ring_full": counters.get("engine.ring.full", 0),
        },
        # Serving-plane rollup: summed rejection/shed counters, the
        # world in-flight per class (summed gauges), and which ranks are
        # saturated right now (their classes at budget).
        "admission": {
            "rejected": counters.get("engine.admission.rejected", 0),
            "shed": counters.get("engine.admission.shed", 0),
            "inflight": {
                cls: sum((gauges_per_rank.get(
                    f"engine.admission.inflight.{cls}") or {}).values())
                for cls in ("high", "normal", "low")},
            "saturated_ranks": {str(r): cls for r, cls
                                in sorted(saturated_ranks.items())},
        },
        "counters": counters,
        "gauges": gauges,
        "step": {"sparkline": sparkline,
                 "per_rank_last": {str(r): v for r, v
                                   in sorted(step_last.items())}},
        "doctor": doctor,
    }


class FleetAggregator:
    """Rank 0's merged world view. Reads every rank's snapshot key for
    the CURRENT (generation, epoch) through any kv-like object exposing
    ``try_get`` (FileKV in production, LocalKV in unit tests), judges
    staleness by its OWN clock against the snapshot seq (a frozen seq
    past the lease = STALE; wall-clock skew between hosts never enters
    the verdict), folds the elastic death notes in as DEAD, and merges.
    Nothing here blocks: a missing or dead rank's key is simply absent
    or stale — the rollup always returns."""

    def __init__(self, kv, nproc: int,
                 lease: Optional[float] = None):
        self._kv = kv
        self._nproc = nproc
        self._lease = fleet_lease_s() if lease is None else lease
        # rank -> (seq, monotonic time the seq last ADVANCED)
        self._beats: Dict[int, Tuple[int, float]] = {}
        self._lock = threading.Lock()

    def collect(self, generation: Optional[int] = None,
                epoch: Optional[int] = None,
                now: Optional[float] = None,
                extra: Optional[List[dict]] = None) -> dict:
        """One rollup pass. ``extra`` prepends already-local snapshots
        (rank 0 includes its own registry directly — its view must not
        depend on reading back its own KV write)."""
        if generation is None or epoch is None:
            g, e = _world_coords()
            generation = g if generation is None else generation
            epoch = e if epoch is None else epoch
        now = time.monotonic() if now is None else now
        snaps: List[dict] = list(extra or [])
        # Ranks handed in directly are live by construction (rank 0's
        # own registry in fleet_report) — the seq lease only judges
        # ranks read back through the KV plane.
        live = {int(s["rank"]) for s in snaps}
        have = set(live)
        for rank in range(self._nproc):
            if rank in have:
                continue
            raw = None
            try:
                raw = self._kv.try_get(snapshot_key(generation, epoch,
                                                    rank))
            except Exception:  # a failing KV must not wedge the rollup
                LOG.debug("fleet collect failed for rank %d", rank,
                          exc_info=True)
            if raw is None:
                continue
            try:
                snap = json.loads(raw)
            except ValueError:
                continue  # torn/foreign value: skip, never raise
            snaps.append(snap)

        dead = set()
        try:
            from horovod_tpu.core import elastic

            summary = elastic.world_summary()
            if summary:
                dead = {int(r) for r in summary.get("dead", {})}
        except Exception:  # pragma: no cover - defensive
            pass

        states: Dict[int, str] = {}
        with self._lock:
            for snap in snaps:
                rank = int(snap["rank"])
                seq = int(snap.get("seq", 0))
                prev = self._beats.get(rank)
                if rank in live or prev is None or seq > prev[0]:
                    self._beats[rank] = (max(seq, prev[0] if prev else 0),
                                         now)
                    states[rank] = "OK"
                elif now - prev[1] > self._lease:
                    states[rank] = "STALE"
                else:
                    states[rank] = "OK"
                if rank in dead:
                    states[rank] = "DEAD"
        return merge_snapshots(snaps, states)


# ---------------------------------------------------------------------------
# Process-wide wiring (topology.init / telemetry endpoint / hvd API)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_publisher: Optional[FleetPublisher] = None
_aggregator: Optional[FleetAggregator] = None


def maybe_start(rank: int, nproc: int):
    """Start the per-rank publisher (every rank) and the aggregator
    (rank 0) when a fleet directory resolves. Called from
    ``topology.init``; idempotent; never raises."""
    global _publisher, _aggregator
    if not enabled():
        return
    d = fleet_dir()
    if not d:
        return
    try:
        from horovod_tpu.core.elastic import FileKV

        with _lock:
            if _publisher is None:
                _publisher = FleetPublisher(FileKV(d), rank)
                _publisher.start()
            if rank == 0 and _aggregator is None:
                _aggregator = FleetAggregator(FileKV(d), nproc)
    except Exception:  # observability must never break init
        LOG.warning("fleet plane failed to start", exc_info=True)


def stop():
    global _publisher, _aggregator
    with _lock:
        pub, _publisher = _publisher, None
        _aggregator = None
    if pub is not None:
        pub.stop()


def fleet_report() -> dict:
    """The merged world view. On rank 0 with the plane up this covers
    every publishing rank (STALE/DEAD marked, never blocking); without
    a KV plane (single process, plane off) it degrades to a one-rank
    rollup of the local registry — same shape either way."""
    try:
        from horovod_tpu.common import topology as topo

        rank = topo.process_index() if topo.is_initialized() else 0
    except Exception:  # pragma: no cover - defensive
        rank = 0
    with _lock:
        agg = _aggregator
    local = local_snapshot(rank=rank)
    if agg is None:
        return merge_snapshots([local])
    return agg.collect(extra=[local])


def report_from_dir(directory: str,
                    now: Optional[float] = None) -> dict:
    """Cold-scan rollup for the console: read every snapshot file in a
    fleet directory (FileKV flattens ``hvd/fleet/g{g}/e{e}/p{r}`` to
    ``hvd~fleet~...``), keep the newest (generation, epoch), and merge.
    A console has no seq history, so staleness is judged by snapshot
    wall age against the lease — good enough for eyes on a screen; the
    in-process aggregator keeps the clock-skew-proof seq rule."""
    import re as _re

    now = time.time() if now is None else now
    pat = _re.compile(r"^hvd~fleet~g(\d+)~e(\d+)~p(\d+)$")
    found: Dict[Tuple[int, int], List[dict]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return merge_snapshots([])
    for fname in names:
        m = pat.match(fname)
        if not m:
            continue
        try:
            with open(os.path.join(directory, fname)) as fh:
                snap = json.loads(fh.read())
        except (OSError, ValueError):
            continue  # torn/retired key mid-scan: skip
        found.setdefault((int(m.group(1)), int(m.group(2))),
                         []).append(snap)
    if not found:
        return merge_snapshots([])
    snaps = found[max(found)]
    lease = fleet_lease_s()
    states = {int(s["rank"]): ("STALE" if now - s.get("wall", now) > lease
                               else "OK")
              for s in snaps}
    return merge_snapshots(snaps, states)


def prometheus_extra() -> str:
    """Per-rank-labeled Prometheus series appended to rank 0's
    ``/metrics`` (empty off rank 0 or with the plane down). Fleet series
    are labeled so one scrape of rank 0 carries the whole world."""
    with _lock:
        agg = _aggregator
    if agg is None:
        return ""
    # Same view as /fleet: the KV-merged world plus this rank's LIVE
    # registry (a scrape between beats must not lag a publish interval).
    report = fleet_report()
    lines: List[str] = []
    lines.append("# TYPE hvd_fleet_size gauge")
    lines.append(f"hvd_fleet_size {report['size']}")
    lines.append(f"hvd_fleet_epoch {report['epoch']}")
    for rank, info in report["ranks"].items():
        state = info.get("state", "OK")
        lines.append(
            f'hvd_fleet_rank_up{{rank="{rank}"}} '
            f"{1 if state == 'OK' else 0}")
        lines.append(
            f'hvd_fleet_rank_age_seconds{{rank="{rank}"}} '
            f"{info['age_s']:.3f}")
        if info.get("queue_depth") is not None:
            lines.append(
                f'hvd_fleet_queue_depth{{rank="{rank}"}} '
                f"{info['queue_depth']:g}")
        if info.get("pool_bytes") is not None:
            lines.append(
                f'hvd_fleet_pool_bytes_resident{{rank="{rank}"}} '
                f"{info['pool_bytes']:g}")
        if info.get("step_s") is not None:
            lines.append(
                f'hvd_fleet_step_seconds{{rank="{rank}"}} '
                f"{info['step_s']:.6g}")
    for op, q in report["ops"].items():
        for label in ("p50_us", "p99_us", "p999_us"):
            if q.get(label) is not None:
                lines.append(
                    f'hvd_fleet_latency_{label}{{op="{op}"}} '
                    f"{q[label]:g}")
    return "\n".join(lines) + "\n" if lines else ""
