"""NativeEngine — the C++ engine behind the same API as the Python Engine.

The scheduler, tensor table, fusion loop, handle manager, stall watchdog and
timeline live in C++ (libhvdcore, reference: horovod/common/operations.cc);
the data plane is still XLA — the C++ loop calls back into
:class:`horovod_tpu.core.engine.JaxExecutor` through a ctypes trampoline.
This mirrors the reference's split where the C++ core calls into
framework-owned allocators/streams through the abstract interfaces of
common/common.h:77-110.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import threading
import time
from typing import List, Optional

import numpy as np

from horovod_tpu.core import bufferpool as bpool, faultline as flt, \
    native, numerics as numx, telemetry as tele, timeline as tl
from horovod_tpu.core.engine import (
    STALL_WARNING_TIME_S,
    WIRE_CODES,
    WIRE_NAMES,
    AdmissionRejected,
    CancelledError,
    CollectiveTimeout,
    DuplicateNameError,
    EngineError,
    JaxExecutor,
    ShutdownError,
    SubmitRequest,
    _freeze_donated,
    _multi_controller,
    _negotiated,
    admission_burst_inject,
    admission_from_env,
    build_admission_summary,
    check_wire_exclusive,
    collective_deadline_from_env,
    config_from_env,
    doctor_on_hang,
    make_autotuner,
    priority_from_env,
    quiesce_drain,
    record_admission,
    record_cache_config,
    record_submit,
    record_submit_batch,
    resolve_priority,
    resolve_wire_policy,
    wire_dcn_policy_from_env,
    wire_policy_from_env,
)

# Engine wire dtypes (the role MPIDataType plays in the reference,
# common/mpi_message.h:26-37).
_DTYPES = [
    np.dtype(np.float32), np.dtype(np.float64), np.dtype(np.float16),
    np.dtype(np.int8), np.dtype(np.uint8), np.dtype(np.int16),
    np.dtype(np.uint16), np.dtype(np.int32), np.dtype(np.uint32),
    np.dtype(np.int64), np.dtype(np.uint64), np.dtype(np.bool_),
    np.dtype(np.complex64), np.dtype(np.complex128),
]
try:  # bf16 — TPU's native dtype; numpy spells it via ml_dtypes
    import ml_dtypes

    _DTYPES.append(np.dtype(ml_dtypes.bfloat16))
except ImportError:  # pragma: no cover
    pass
_DTYPE_CODE = {dt: i for i, dt in enumerate(_DTYPES)}

_OPS = {"allreduce": 0, "allgather": 1, "broadcast": 2}
_OPS_INV = {v: k for k, v in _OPS.items()}

LOG = logging.getLogger("horovod_tpu.native_engine")


def _args_body(d: dict) -> bytes:
    """Render a dict as the brace-less JSON object body the C++ timeline
    hooks expect (they wrap it in ``{"args":{...}}`` themselves)."""
    return json.dumps(d)[1:-1].encode()


def _write_cstring(lib, out_pp, text: bytes):
    """Hand a string to C through an hvd_alloc'd buffer (the engine frees
    it — Python-owned bytes would dangle once the callback frame drops)."""
    ptr = lib.hvd_alloc(len(text) + 1)
    ctypes.memmove(ptr, text + b"\0", len(text) + 1)
    out_pp[0] = ptr


def _make_negotiator(engine):
    """ctypes trampoline: libhvdcore's loop thread calls this each cycle
    with the pending-entry table; we run one KV negotiation round
    (core/coordinator.py) and hand back the agreed decision."""
    import json

    from horovod_tpu.core import coordinator as coord

    lib = engine._lib

    @native.NEG_FN
    def neg(ctx, table_json, out_pp):
        try:
            import time

            c = engine._coordinator
            rows = json.loads(table_json.decode())
            metas = [
                coord.RequestMeta(
                    name=r["n"], op=_OPS_INV[r["o"]],
                    dtype=str(_DTYPES[r["d"]]), itemsize=r["i"],
                    shape=tuple(r["s"]), average=bool(r["a"]),
                    root_rank=r["r"], prescale=r["p"], age_s=r["t"],
                    nbytes=r["b"],
                    compression=WIRE_NAMES.get(r.get("w", 0), "none"),
                    compression_dcn=WIRE_NAMES.get(r.get("wd", 0), "none"),
                    priority=int(r.get("y", 1)))
                for r in rows
            ]
            t_neg = time.monotonic()
            decision = c.negotiate(metas)
            tele.REGISTRY.histogram("engine.negotiation_s").observe(
                time.monotonic() - t_neg)
            if c.clock_ready and not engine._clock_synced:
                # Anchor exchange complete: embed rank 0's clock bridge
                # (+ the measured KV round trip) in the trace metadata
                # so per-rank files merge on one time base.
                engine._clock_synced = True
                engine._emit_clock_meta(c.clock_offset_us, c.clock_rtt_us)
            if c.last_tables:
                # Per-process readiness instants inside the NEGOTIATE_*
                # span (reference: timeline.cc:106-130): the C++ writer
                # owns the file, the tables live here — mark through the
                # engine's instant hook.
                seen = engine._ready_marked
                live = {m.name for m in metas}
                for stale in [n for n in seen if n not in live]:
                    del seen[stale]
                for m in metas:
                    marked = seen.setdefault(m.name, set())
                    for p, names in c.last_tables.items():
                        if p not in marked and m.name in names:
                            marked.add(p)
                            lib.hvd_engine_timeline_instant(
                                engine._ptr, m.name.encode(),
                                tl.RANK_READY.encode(),
                                f'"process":{p}'.encode())
                # A name's seen-set lives exactly as long as its pending
                # instance: recurring tensors (per-step gradients) are
                # re-submitted before an empty round could prune them,
                # so clear at execution — the python twin's per-_Entry
                # lifetime, same observable semantics.
                for g in decision.groups:
                    for i in g.indices:
                        seen.pop(metas[i].name, None)
            lines = [f"p {decision.cycle_time_s} "
                     f"{decision.fusion_threshold}",
                     # Whether this round took the response-cache fast
                     # path — the C++ loop stamps it as the `cached` arg
                     # on the NEGOTIATE_* span ends it owns.
                     f"c {1 if decision.cached else 0}"]
            if decision.idle_backoff_s:
                lines.append(f"w {decision.idle_backoff_s}")
            for g in decision.groups:
                idxs = ",".join(map(str, g.indices))
                if g.error:
                    lines.append(f"e {idxs} " + g.error.replace("\n", " "))
                else:
                    lines.append(f"g {idxs}")
            _write_cstring(lib, out_pp, "\n".join(lines).encode())
            return 0
        except Exception as exc:  # peer shutdown / timeout / KV failure
            msg = str(exc)
            if not coord.is_shutdownish(exc):
                # A hung negotiation (timeout, KV failure) gets the
                # post-mortem flight-recorder dump; a clean peer/local
                # shutdown does not — same rule as the python twin.
                engine._dump_flight(f"negotiation failed: {msg}",
                                    kind="negotiation")
            _write_cstring(lib, out_pp, msg.encode()[:4000])
            return 1

    return neg


def _make_callback(executor):
    lib = native.load_library()
    lib.hvd_alloc.restype = ctypes.c_void_p
    lib.hvd_alloc.argtypes = [ctypes.c_longlong]

    @native.EXEC_FN
    def cb(ctx, req_p, res_p):
        req, res = req_p.contents, res_p.contents
        try:
            if req.op == 3:  # TICK: end-of-cycle traffic report
                pm = getattr(executor, "param_manager", None)
                if pm is not None:
                    pm.update(int(req.count))
                return 0
            dtype = _DTYPES[req.dtype_num]
            nbytes = int(req.count) * int(req.itemsize)
            # Zero-copy view of the engine's buffer: the C++ loop thread
            # is blocked inside this callback for its whole duration, so
            # the pointer is stable and a defensive copy (one full pass
            # over every payload, removed with the buffer pool) would
            # buy nothing. Flagged read-only — executors only READ their
            # input; results go to separate (pooled) buffers.
            buf = np.frombuffer(
                (ctypes.c_char * nbytes).from_address(req.data),
                dtype=dtype)
            buf.flags.writeable = False
            # Same-size results land at req.out: == req.data (in place)
            # unless the input was donated, where the engine supplied a
            # pooled bounce buffer instead.
            dst = req.out if req.out else req.data
            executor.last_stage_s = 0.0
            executor.last_wire_bytes = 0
            executor.last_wire_compressed = 0
            executor.last_wire_bytes_dcn = 0
            executor.last_wire_bytes_ici = 0
            if req.op == 0:  # allreduce (possibly fused)
                if req.prescale != 1.0:
                    buf = buf * req.prescale
                # Wire policy from the request (the C++ loop's fusion
                # key keeps batches policy-uniform); the shared data
                # plane applies the quantized format per chunk, which is
                # what makes the two engines' digests bit-identical.
                executor.wire_policy = WIRE_NAMES.get(req.wire, "none")
                executor.wire_policy_dcn = WIRE_NAMES.get(req.wire_dcn,
                                                          "none")
                out = executor.allreduce(buf, bool(req.average))
                out = np.ascontiguousarray(out, dtype=dtype)
                ctypes.memmove(dst, out.ctypes.data, nbytes)
                res.data, res.nbytes = dst, nbytes
                res.ndim, res.shape[0] = 1, req.count
            elif req.op == 1:  # allgather: output is bigger — C-owned buf
                shape = tuple(req.shape[i] for i in range(req.ndim))
                out = executor.allgather(buf.reshape(shape))
                out = np.ascontiguousarray(out, dtype=dtype)
                ptr = lib.hvd_alloc(out.nbytes)
                if not ptr:
                    raise MemoryError("hvd_alloc failed")
                ctypes.memmove(ptr, out.ctypes.data, out.nbytes)
                res.data, res.nbytes = ptr, out.nbytes
                res.ndim = out.ndim
                for i, s in enumerate(out.shape):
                    res.shape[i] = s
            elif req.op == 2:  # broadcast: same shape, in place
                shape = tuple(req.shape[i] for i in range(req.ndim))
                out = executor.broadcast(buf.reshape(shape), int(req.root_rank))
                out = np.ascontiguousarray(out, dtype=dtype)
                ctypes.memmove(dst, out.ctypes.data, nbytes)
                res.data, res.nbytes = dst, nbytes
                res.ndim = out.ndim
                for i, s in enumerate(out.shape):
                    res.shape[i] = s
            else:
                raise ValueError(f"unknown op {req.op}")
            # Staging time the executor measured (WAIT_FOR_DATA span).
            res.stage_s = float(getattr(executor, "last_stage_s", 0.0))
            # Wire bytes the call shipped — the engine folds them into
            # hvd_engine_stats (parity with the python twin's
            # record_wire counters).
            res.wire_bytes = int(getattr(executor, "last_wire_bytes", 0))
            res.wire_compressed = int(
                getattr(executor, "last_wire_compressed", 0))
            res.wire_dcn = int(
                getattr(executor, "last_wire_bytes_dcn", 0))
            res.wire_ici = int(
                getattr(executor, "last_wire_bytes_ici", 0))
            return 0
        except Exception as exc:  # surfaced at synchronize()
            msg = str(exc).encode()[:255]
            res.error = msg
            return 1

    return cb


class NativeEngine:
    """Same surface as :class:`horovod_tpu.core.engine.Engine`, backed by
    libhvdcore."""

    def __init__(self, executor=None, cycle_time_s: Optional[float] = None,
                 fusion_threshold: Optional[int] = None,
                 stall_warning_s: float = STALL_WARNING_TIME_S,
                 timeline_path: Optional[str] = None):
        (self.cycle_time_s, self.fusion_threshold, stall_warning_s,
         self.cache_capacity) = config_from_env(
            cycle_time_s, fusion_threshold, stall_warning_s)
        self._stall_warning_s = stall_warning_s
        if timeline_path is None:
            timeline_path = tl.timeline_path_from_env() or ""

        self._lib = native.load_library()
        self._executor = executor or JaxExecutor()
        # Python-side buffer pool: executor output/staging buffers and
        # synchronize() result buffers (the C++ loop keeps its own twin
        # inside libhvdcore for entry/fusion/result buffers; both feed
        # the same engine.pool.* counters — the C++ side through the
        # stats sync below).
        self._pool = bpool.BufferPool(own_gauge=False)
        if getattr(self._executor, "pool", None) is None:
            self._executor.pool = self._pool
        # Donated submit buffers, pinned until their handle retires: the
        # C++ entry references them in place (read-only), so Python must
        # keep them alive until completion.
        self._donated: dict = {}
        # Engine-wide default wire format (HVD_COMPRESSION) — same rule
        # and fail-fast as the python twin.
        self.wire_default = wire_policy_from_env()
        # Per-tier DCN default (HVD_COMPRESSION_DCN) for the
        # hierarchical two-phase route — mutually exclusive with a
        # uniform wire policy on any one request (check_wire_exclusive).
        self.wire_dcn_default = wire_dcn_policy_from_env()
        # Serving plane: engine-wide default priority class
        # (HVD_PRIORITY) and per-class admission budgets
        # (HVD_ADMISSION_MAX_{INFLIGHT,BYTES}[_<CLASS>]) — same knobs,
        # same fail-fast as the python twin; the budgets are pushed into
        # the C++ engine below so its lock-free submit path enforces
        # them.
        self.priority_default = priority_from_env()
        self.adm_max_inflight, self.adm_max_bytes = admission_from_env()
        # Deadline/cancel/drain plane (same knobs as the python twin):
        # the HVD_COLLECTIVE_DEADLINE_S default, the quiesce reason once
        # admission closes, and donated buffers whose waiter a deadline
        # released while the C++ entry may STILL reference them — parked
        # for process lifetime (the leak-the-wedged doctrine; freeing
        # them under a wedged executor's zero-copy read would be UB).
        self.default_deadline_s = collective_deadline_from_env()
        self._quiesced: Optional[str] = None
        self._parked_donations: list = []
        self._ready_marked: dict = {}  # name -> processes marked RANK_READY
        if timeline_path:
            # Staging time feeds the WAIT_FOR_DATA spans; only measured
            # (it costs a device sync) while a timeline is recording.
            self._executor.measure_staging = True
        self._cb = _make_callback(self._executor)  # keep trampoline alive
        self._ptr = self._lib.hvd_engine_create(
            float(self.cycle_time_s), int(self.fusion_threshold),
            float(stall_warning_s), timeline_path.encode())
        self._lib.hvd_engine_set_executor(self._ptr, self._cb, None)
        self._lib.hvd_engine_set_admission(
            self._ptr,
            (ctypes.c_longlong * 3)(*self.adm_max_inflight),
            (ctypes.c_longlong * 3)(*self.adm_max_bytes))
        # Distributed-tracing clock metadata: map the C++ timeline clock
        # (trace ts 0) onto the wall clock and record this process's
        # wall↔monotonic bridge as the default common-base offset (see
        # core/timeline.py HVD_CLOCK); replaced by rank 0's bridge once
        # the coordinator's anchor exchange completes.
        self._rank = tl._process_index()
        self._clock_synced = False
        self._emit_clock_meta(None, None)
        # Post-mortem hook: SIGUSR1 dumps the C++ flight-recorder ring.
        tl.install_sigusr1(self._dump_sigusr1)
        # Negotiated multi-controller path: register the control-plane
        # trampoline; it is activated lazily once topology knows several
        # processes exist (set_params is re-applied at hvd.init()).
        self._coordinator = None
        self._neg = _make_negotiator(self)  # keep trampoline alive
        self._lib.hvd_engine_set_negotiator(self._ptr, self._neg, None)
        self._maybe_activate_negotiation()
        # Deterministic multi-controller ordering (same rule as the python
        # twin's _run_cycle sort); re-evaluated in set_params since topology
        # may come up after engine construction.
        if self._coordinator is None:
            self._lib.hvd_engine_set_sort_by_name(
                self._ptr, int(_multi_controller()))
        self._meta: dict = {}  # handle -> (np.dtype, name): result
        # decode + numerics attribution at synchronize

        # Autotuner: the C++ loop reports per-cycle traffic through TICK
        # callbacks; tuned values land back via hvd_engine_set_params.
        self._param_manager = make_autotuner(self)
        self._executor.param_manager = self._param_manager

        # Execution-side telemetry rides the stats C API: a registry sync
        # hook folds counter deltas in right before every snapshot, so
        # both engines surface the SAME counter names (submit-side
        # counters are recorded in _enqueue below, which is Python).
        self._last_stats: dict = {}
        self._last_latency: dict = {}
        self._stats_lock = threading.Lock()
        tele.REGISTRY.register_sync(self._collect_stats)

        # Stall post-mortem parity with the python twin's _check_stalls:
        # the C++ watchdog prints the warning, this thread dumps the
        # flight recorder when in-flight work stops making progress.
        self._stall_stop = threading.Event()
        if stall_warning_s > 0:
            self._stall_thread = threading.Thread(
                target=self._stall_dump_loop,
                name="hvd-native-stall-dump", daemon=True)
            self._stall_thread.start()

    # Registry counter name <- HvdStats field (the parity contract with
    # the python engine's record_* helpers in core/engine.py).
    _STAT_COUNTERS = (
        ("engine.completed", "completed"),
        ("engine.errors", "errors"),
        ("engine.fused.batches", "fused_batches"),
        ("engine.fused.tensors", "fused_tensors"),
        ("engine.fused.bytes", "fused_bytes"),
        ("engine.cycles", "cycles"),
        ("engine.cycle_seconds_total", "cycle_seconds"),
        ("engine.wire_bytes", "wire_bytes"),
        ("engine.wire_bytes.compressed", "wire_bytes_compressed"),
        # Per-tier split of the hierarchical two-phase route; the python
        # twin feeds the same names through record_wire.
        ("engine.wire_bytes.dcn", "wire_bytes_dcn"),
        ("engine.wire_bytes.ici", "wire_bytes_ici"),
        # The C++ pool's events fold into the SAME counters the python
        # pool feeds (core/bufferpool.py).
        ("engine.pool.hits", "pool_hits"),
        ("engine.pool.misses", "pool_misses"),
        ("engine.pool.checkouts", "pool_checkouts"),
        # Deadline/cancel plane — the python twin's counters of the
        # same names are fed in its sweep/_complete paths.
        ("engine.deadline_exceeded", "deadline_exceeded"),
        ("engine.cancelled", "cancelled"),
        # Batched-submit plane: submit-ring pressure and name-bound pool
        # reuse. The python twin's names are pinned into existence by
        # record_submit_batch / BufferPool.snapshot_bound (it has no
        # ring, so the ring pair stays 0 there).
        ("engine.ring.full", "ring_full"),
        ("engine.ring.spins", "ring_spins"),
        ("engine.pool.bound_hits", "pool_bound_hits"),
        # Serving plane: synchronous admission rejections and
        # deadline-aware fast-fail sheds. The C++ submit path counts
        # them in its own atomics (it never calls back into python), so
        # the shim must NOT also call record_admission_rejected — the
        # fold below is the single writer for these names.
        ("engine.admission.rejected", "admission_rejected"),
        ("engine.admission.shed", "admission_shed"),
    )

    # Registry histogram name <- hvd_engine_latency field (the parity
    # contract with record_phase / record_complete_latency in
    # core/engine.py; bucket edges are parity-checked from source by
    # hvdcheck rule parity-latency). The C++ loop observed into its own
    # bucket arrays; _collect_stats folds count DELTAS into the registry
    # histograms, so merged values stay exact (same buckets, sum counts).
    _LATENCY_HISTS = (
        ("engine.latency.allreduce", "allreduce"),
        ("engine.latency.allgather", "allgather"),
        ("engine.latency.broadcast", "broadcast"),
        ("engine.phase.queue", "phase_queue"),
        ("engine.phase.negotiate", "phase_negotiate"),
        ("engine.phase.memcpy", "phase_memcpy"),
        ("engine.phase.exec", "phase_exec"),
        ("engine.deadline.margin", "deadline_margin"),
        # Per-priority-class completion latency (serving plane SLO
        # view) — the python twin's record_complete_latency feeds the
        # same names.
        ("engine.latency.class.high", "class_high"),
        ("engine.latency.class.normal", "class_normal"),
        ("engine.latency.class.low", "class_low"),
    )

    def _collect_stats(self):
        """Fold the C++ loop's counters into the process-wide registry
        (delta since the previous collect — counters stay monotonic
        across engine generations). Locked: two concurrent snapshots
        computing the same delta would double-count it."""
        with self._stats_lock:
            if self._ptr is None:
                return
            st = native.HvdStats()
            self._lib.hvd_engine_get_stats(self._ptr, ctypes.byref(st))
            for reg_name, field in self._STAT_COUNTERS:
                value = getattr(st, field)
                delta = value - self._last_stats.get(field, 0)
                if delta:
                    tele.REGISTRY.counter(reg_name).inc(delta)
                    self._last_stats[field] = value
            tele.REGISTRY.gauge("engine.queue_depth").set(
                int(st.queue_depth))
            record_admission([int(st.admission_inflight_high),
                              int(st.admission_inflight_normal),
                              int(st.admission_inflight_low)])
            # Resident bytes is a gauge: C++ pool + this engine's python
            # pool together (one data plane, one occupancy number).
            tele.REGISTRY.gauge("engine.pool.bytes_resident").set(
                int(st.pool_bytes_resident) + self._pool.bytes_resident)
            lat = native.HvdLatency()
            self._lib.hvd_engine_get_latency(self._ptr, ctypes.byref(lat))
            for hist_name, field in self._LATENCY_HISTS:
                counts = list(getattr(lat, field))
                prev = self._last_latency.get(field)
                deltas = (counts if prev is None else
                          [c - p for c, p in zip(counts, prev)])
                if any(deltas):
                    sum_now = float(getattr(lat, field + "_sum"))
                    tele.REGISTRY.histogram(hist_name).add_counts(
                        deltas,
                        sum_now - self._last_latency.get(field + "_sum", 0.0))
                    self._last_latency[field] = counts
                    self._last_latency[field + "_sum"] = sum_now

    def _emit_clock_meta(self, offset_us: Optional[int],
                         rtt_us: Optional[int]):
        """Write an HVD_CLOCK metadata event through the C++ timeline.
        ``offset_us=None`` means 'use this process's own wall↔monotonic
        bridge' (the single-host-exact default); the coordinator's anchor
        exchange later supplies rank 0's bridge + the measured KV round
        trip. The merge tool uses the LAST HVD_CLOCK event per trace."""
        if self._ptr is None:
            return
        now_us = int(self._lib.hvd_engine_timeline_now(self._ptr))
        wall = time.time()
        mono = time.monotonic()
        args = {"rank": self._rank,
                "epoch_wall_us": int(wall * 1e6) - now_us,
                "offset_us": (int((wall - mono) * 1e6)
                              if offset_us is None else int(offset_us))}
        if rtt_us is not None:
            args["rtt_us"] = int(rtt_us)
        self._lib.hvd_engine_timeline_meta(
            self._ptr, tl.CLOCK_SYNC.encode(), _args_body(args))

    def recent_events(self) -> List[dict]:
        """The C++ engine's flight-recorder ring (always on, bounded by
        HVD_FLIGHT_RECORDER_SIZE) — same event shape as the python
        twin's ``Timeline.recent()``."""
        ptr = self._ptr  # snapshot: a racing shutdown() nulls the attr,
        # but the engine object itself is deliberately leaked, so a
        # captured pointer stays valid for the whole call.
        if ptr is None:
            return []
        cap = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.hvd_engine_recent_events(ptr, buf, cap)
            if n <= cap:
                return json.loads(buf.value.decode() or "[]")
            cap = int(n) + 1  # ring grew past the buffer — retry sized

    def _dump_flight(self, reason: str, kind: Optional[str] = None):
        """Dump the C++ ring (+ telemetry snapshot) — stalls, deadline
        expiries, negotiation failures and SIGUSR1 route here. ``kind``
        tags hang-class dumps exactly like the python twin's: those
        embed the per-entry inspect table (``hvd_engine_inspect``),
        engage the cross-rank hang doctor (core/doctor.py), and key the
        dump rate limit separately so a prior unrelated dump cannot
        suppress a hang post-mortem. Never raises."""
        try:
            events = self.recent_events()
        except Exception:
            events = []
        table = None
        verdict = None
        if kind is not None:
            try:
                table = self.inspect()
            except Exception:
                table = None
            verdict = doctor_on_hang(reason, kind, table, self._rank)
        tl.dump_and_warn(events, reason, self._rank, LOG, kind=kind,
                         inspect=table, verdict=verdict)

    def _dump_sigusr1(self, reason: str):
        """SIGUSR1 entry point: an on-demand live-hang post-mortem —
        the dump embeds the inspect table and engages the doctor."""
        self._dump_flight(reason, kind="sigusr1")

    def _stall_dump_loop(self):
        """Dump the flight recorder when tensors sit in flight with no
        completions/errors for a full stall window — the python twin
        dumps from _check_stalls; the C++ loop's own watchdog only
        warns (the hung thread may be inside the executor callback, so
        detection must live outside it). Heuristic mirror over the
        stats snapshot: depth > 0 with frozen progress counters."""
        interval = max(self._stall_warning_s / 5.0, 0.01)
        last_progress = None
        stuck_since = None
        last_dump = 0.0
        while not self._stall_stop.wait(interval):
            ptr = self._ptr
            if ptr is None:
                return
            st = native.HvdStats()
            try:
                self._lib.hvd_engine_get_stats(ptr, ctypes.byref(st))
            except Exception:
                return
            now = time.monotonic()
            progress = (int(st.completed), int(st.errors))
            if int(st.queue_depth) > 0 and progress == last_progress:
                if stuck_since is None:
                    stuck_since = now
                elif (now - stuck_since > self._stall_warning_s
                        and now - last_dump > self._stall_warning_s):
                    last_dump = now
                    reason = (f"stalled: {int(st.queue_depth)} tensor(s) "
                              f"in flight with no completions for "
                              f"{int(now - stuck_since)}s")
                    self._dump_flight(reason, kind="stall")
                    # Sentinel parity with the python twin: the stall
                    # becomes /healthz state + verdict attribution.
                    try:
                        from horovod_tpu.core import sentinel as _sentinel

                        _sentinel.note_stall(reason, self._rank)
                    except Exception:
                        pass
            else:
                stuck_since = None
            last_progress = progress

    def _maybe_activate_negotiation(self):
        """Build the coordinator + flip the C++ loop into negotiated mode
        once a multi-controller world with a KV service is known."""
        if self._coordinator is not None or self._ptr is None:
            return
        if not _multi_controller():
            return
        from horovod_tpu.core import coordinator as coord

        self._coordinator = coord.make_coordinator(
            self.cycle_time_s, self.fusion_threshold, self._stall_warning_s,
            cache_capacity=self.cache_capacity)
        if self._coordinator is not None:
            self._lib.hvd_engine_set_negotiation_active(self._ptr, 1)

    def _enqueue(self, op: str, name: str, tensor: np.ndarray,
                 average: bool = False, root_rank: int = 0,
                 prescale: float = 1.0,
                 compression: Optional[str] = None,
                 compression_dcn: Optional[str] = None,
                 donate: bool = False,
                 deadline_ms: Optional[float] = None,
                 priority: Optional[str] = None) -> int:
        # Fault site engine.admit (core/faultline.py, burst mode): pile
        # synthetic low-priority work onto the queue BEFORE this submit
        # is admitted — drives the class budget toward saturation so
        # admission rejections can be rehearsed. Same placement as the
        # python twin: single-submit path only.
        admission_burst_inject(self, name)
        # Fault site engine.submit (core/faultline.py) — in the python
        # shim, BEFORE the C++ enqueue, so both engines fail a submit at
        # the same point with the same observable shape.
        injected = flt.engine_submit(name)
        if injected is not None:
            raise EngineError(injected)
        if self._ptr is None:
            raise ShutdownError("engine is shut down")
        if self._quiesced is not None:
            # Admission closed (quiesce): same descriptive fail-fast as
            # the python twin.
            raise EngineError(
                f"engine is draining ({self._quiesced}): submissions "
                "are closed — the engine is completing in-flight work "
                "before shutdown (quiesce)")
        if deadline_ms is not None:
            deadline_s = deadline_ms / 1000.0 if deadline_ms > 0 else 0.0
        else:
            deadline_s = self.default_deadline_s or 0.0
        tensor = np.asarray(tensor)
        donate = donate and tensor.flags["C_CONTIGUOUS"]
        if not donate:
            tensor = np.ascontiguousarray(tensor)
        if tensor.dtype not in _DTYPE_CODE:
            raise EngineError(f"unsupported dtype {tensor.dtype}")
        if tensor.ndim > 8:
            raise EngineError("tensors with >8 dims are not supported")
        # Only allreduce has a quantized reduction; allgather/broadcast
        # always ship full width — pin 'none' so the negotiated identity
        # matches the python twin's (whose _Entry default does the same)
        # and the timeline never stamps a wire policy on them.
        if op != "allreduce":
            wire = "none"
            wire_dcn = "none"
        else:
            wire = (resolve_wire_policy(compression)
                    if compression is not None else self.wire_default)
            wire_dcn = (resolve_wire_policy(compression_dcn)
                        if compression_dcn is not None
                        else self.wire_dcn_default)
            check_wire_exclusive(wire, wire_dcn, name)
        prio = (self.priority_default if priority is None
                else resolve_priority(priority, name))
        flipped = False
        if donate:
            # Ownership handoff: the C++ entry references this buffer in
            # place (read-only — results go to pooled bounce buffers);
            # flag the view unwriteable so an in-process mutation raises,
            # and pin it until the handle retires.
            flipped = _freeze_donated(tensor)
        err = ctypes.create_string_buffer(256)
        shape = (ctypes.c_longlong * max(tensor.ndim, 1))(*tensor.shape)
        h = self._lib.hvd_engine_enqueue(
            self._ptr, _OPS[op], name.encode(), _DTYPE_CODE[tensor.dtype],
            tensor.dtype.itemsize, tensor.ctypes.data, shape, tensor.ndim,
            int(average), int(root_rank), float(prescale),
            int(WIRE_CODES[wire]), int(WIRE_CODES[wire_dcn]), int(donate),
            int(prio), float(deadline_s), err)
        if h < 0:
            # Rejected submit: the engine never took ownership — a
            # donated buffer we froze must become writable again.
            if flipped:
                tensor.flags.writeable = True
            msg = err.value.decode()
            if "already pending" in msg:
                raise DuplicateNameError(msg)
            if "admission" in msg:
                # Covers both the budget rejection and the deadline-
                # aware shed (its message names engine.admission.shed) —
                # the C++ side already counted it.
                raise AdmissionRejected(msg)
            raise ShutdownError(msg)
        if donate:
            self._donated[int(h)] = tensor
        record_submit(op, tensor.nbytes,
                      int(self._lib.hvd_engine_pending(self._ptr)))
        # Numerics (core/numerics.py): local nonfinite at submit is the
        # attribution side of the synchronize-time check — identical
        # counters/verdicts to the python engine's hook.
        numx.engine_note_submit(name, tensor)
        self._meta[h] = (tensor.dtype, name)
        return int(h)

    def allreduce_async(self, name: str, tensor: np.ndarray, average: bool,
                        prescale: float = 1.0,
                        compression: Optional[str] = None,
                        compression_dcn: Optional[str] = None,
                        donate: bool = False,
                        deadline_ms: Optional[float] = None,
                        priority: Optional[str] = None) -> int:
        return self._enqueue("allreduce", name, tensor, average=average,
                             prescale=prescale, compression=compression,
                             compression_dcn=compression_dcn,
                             donate=donate, deadline_ms=deadline_ms,
                             priority=priority)

    def allgather_async(self, name: str, tensor: np.ndarray,
                        donate: bool = False,
                        deadline_ms: Optional[float] = None,
                        priority: Optional[str] = None) -> int:
        return self._enqueue("allgather", name, tensor, donate=donate,
                             deadline_ms=deadline_ms, priority=priority)

    def broadcast_async(self, name: str, tensor: np.ndarray,
                        root_rank: int, donate: bool = False,
                        deadline_ms: Optional[float] = None,
                        priority: Optional[str] = None) -> int:
        return self._enqueue("broadcast", name, tensor, root_rank=root_rank,
                             donate=donate, deadline_ms=deadline_ms,
                             priority=priority)

    def submit_n(self, op: str, requests) -> List[int]:
        """Batched submit through ONE ``hvd_engine_enqueue_n`` call: one
        GIL crossing, one snapshot pass, one ring publish/wakeup for N
        :class:`SubmitRequest` of a single collective op. Returns N
        handles in request order; per-request ``deadline_ms`` /
        ``compression`` / ``donate`` preserved. Duplicate-vs-in-flight
        is DEFERRED to the loop's ring fold: that handle alone fails and
        its ``synchronize`` raises :class:`DuplicateNameError` — same
        contract as the python twin's ``Engine.submit_n``."""
        if op not in _OPS:
            raise EngineError(f"batched submit: unsupported op {op!r}")
        reqs = list(requests)
        n = len(reqs)
        if n == 0:
            raise EngineError("batched submit needs at least one request")
        seen = set()
        for r in reqs:
            if r.name in seen:
                raise DuplicateNameError(
                    f"a collective named '{r.name}' appears twice in one "
                    "batched submit; names must be unique among in-flight "
                    "tensors")
            seen.add(r.name)
        # Fault site engine.submit — once per batch, before any freeze.
        injected = flt.engine_submit(reqs[0].name)
        if injected is not None:
            raise EngineError(injected)
        if self._ptr is None:
            raise ShutdownError("engine is shut down")
        if self._quiesced is not None:
            raise EngineError(
                f"engine is draining ({self._quiesced}): submissions "
                "are closed — the engine is completing in-flight work "
                "before shutdown (quiesce)")
        carr = (native.HvdRequest * n)()
        keep: List[np.ndarray] = []  # tensor keep-alives through the call
        flipped: List[np.ndarray] = []
        donated: dict = {}
        op_code = _OPS[op]
        try:
            for i, r in enumerate(reqs):
                tensor = np.asarray(r.tensor)
                do = bool(r.donate) and tensor.flags["C_CONTIGUOUS"]
                if not do:
                    tensor = np.ascontiguousarray(tensor)
                if tensor.dtype not in _DTYPE_CODE:
                    raise EngineError(f"unsupported dtype {tensor.dtype}")
                if tensor.ndim > 8:
                    raise EngineError(
                        "tensors with >8 dims are not supported")
                if op != "allreduce":
                    wire = "none"
                    wire_dcn = "none"
                else:
                    wire = (resolve_wire_policy(r.compression)
                            if r.compression is not None
                            else self.wire_default)
                    wire_dcn = (resolve_wire_policy(r.compression_dcn)
                                if r.compression_dcn is not None
                                else self.wire_dcn_default)
                    check_wire_exclusive(wire, wire_dcn, r.name)
                if do and _freeze_donated(tensor):
                    flipped.append(tensor)
                if r.deadline_ms is not None:
                    deadline_s = (r.deadline_ms / 1000.0
                                  if r.deadline_ms > 0 else 0.0)
                else:
                    deadline_s = self.default_deadline_s or 0.0
                keep.append(tensor)
                q = carr[i]
                q.op = op_code
                q.dtype_num = _DTYPE_CODE[tensor.dtype]
                q.itemsize = tensor.dtype.itemsize
                q.average = int(r.average)
                q.root_rank = int(r.root_rank)
                q.wire = int(WIRE_CODES[wire])
                q.wire_dcn = int(WIRE_CODES[wire_dcn])
                q.prescale = float(r.prescale)
                q.deadline_s = float(deadline_s)
                q.priority = int(
                    self.priority_default
                    if getattr(r, "priority", None) is None
                    else resolve_priority(r.priority, r.name))
                q.names = r.name.encode()
                q.data = tensor.ctypes.data
                q.out = tensor.ctypes.data
                q.count = tensor.size
                q.ndim = tensor.ndim
                for d, s in enumerate(tensor.shape):
                    q.shape[d] = s
                q.donate = int(do)
                if do:
                    donated[i] = tensor
        except Exception:
            # Rejected mid-build: nothing was handed to C — every buffer
            # frozen above flips back.
            for a in flipped:
                a.flags.writeable = True
            raise
        handles_out = (ctypes.c_longlong * n)()
        err = ctypes.create_string_buffer(256)
        rc = self._lib.hvd_engine_enqueue_n(
            self._ptr, carr, n, handles_out, err)
        if rc != 0:
            for a in flipped:
                a.flags.writeable = True
            msg = err.value.decode()
            if "names must be unique" in msg:
                raise DuplicateNameError(msg)
            if "admission" in msg:
                # Whole-batch all-or-nothing rejection: admission never
                # tears a fused batch (the C++ pre-check refuses the
                # batch before any entry is staged).
                raise AdmissionRejected(msg)
            if "shut down" in msg:
                raise ShutdownError(msg)
            raise EngineError(msg)
        handles = [int(handles_out[i]) for i in range(n)]
        for i, h in enumerate(handles):
            if i in donated:
                self._donated[h] = donated[i]
            self._meta[h] = (keep[i].dtype, reqs[i].name)
        # All N count as submitted — a dup-vs-in-flight verdict only
        # exists at the loop's fold, so the submit-side tally cannot
        # exclude it (the python twin counts identically on purpose).
        # queue_depth=None: reading the pending count would take mu_
        # (and fold the ring) — the stats sync owns the gauge here.
        record_submit_batch(op, [t.nbytes for t in keep], None)
        numx.engine_note_submit_batch([r.name for r in reqs], keep)
        return handles

    def cancel(self, handle: int) -> bool:
        """Cooperative cancel — same contract as the python twin's:
        pre-announce entries retire locally, announced/executing ones
        complete cross-rank and discard; ``synchronize`` then raises
        :class:`CancelledError`. False = unknown or already done."""
        if self._ptr is None:
            return False
        return self._lib.hvd_engine_cancel(self._ptr, handle) == 0

    def quiesce(self, deadline_s: float,
                reason: str = "quiesce requested"):
        """Close admission (new submits fail fast; ``/healthz`` reports
        ``draining``), complete in-flight work within ``deadline_s``,
        report what drained — the python twin's quiesce over the C++
        loop (admission is closed in this binding: every enqueue passes
        through it)."""
        already = self._quiesced is not None
        if not already:
            self._quiesced = reason
        # Shared policy (core/engine.py quiesce_drain): drain loop,
        # draining marker, report shape and log wording are ONE
        # implementation for both engines. No waker needed — the C++
        # loop ticks on its own cycle.
        return quiesce_drain(reason, deadline_s, already,
                             self._pending_names, lambda: None,
                             min(self.cycle_time_s, 0.01))

    def admission_summary(self) -> dict:
        """Serving-plane admission snapshot (same shape as the python
        twin's): queue depth, per-class in-flight counts/bytes against
        their budgets, ``saturated``/``tripped`` flags — read straight
        from the C++ engine's atomics via ``hvd_engine_get_stats``."""
        if self._ptr is None:
            return build_admission_summary(0, [0, 0, 0], [0, 0, 0],
                                           self.adm_max_inflight,
                                           self.adm_max_bytes)
        st = native.HvdStats()
        self._lib.hvd_engine_get_stats(self._ptr, ctypes.byref(st))
        return build_admission_summary(
            int(st.queue_depth),
            [int(st.admission_inflight_high),
             int(st.admission_inflight_normal),
             int(st.admission_inflight_low)],
            [int(st.admission_bytes_high),
             int(st.admission_bytes_normal),
             int(st.admission_bytes_low)],
            self.adm_max_inflight, self.adm_max_bytes)

    def inspect(self) -> List[dict]:
        """Full per-entry state of every in-flight tensor, straight from
        the C++ table (``hvd_engine_inspect``) — the hang doctor's raw
        table, record shape identical to ``Engine.inspect()``
        (``ENGINE_INSPECT_KEYS``; hvdcheck rule ``parity-doctor``
        machine-diffs the two writers). The C side truncates WHOLE
        newline-separated JSON records at the buffer cap and returns the
        TRUE count — grow until every record fits, or a still-wedged
        tensor beyond the cutoff would vanish from the doctor's
        cross-rank diff (each call reads records+count under one lock,
        so the per-call comparison is consistent)."""
        if self._ptr is None:
            return []
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            total = int(self._lib.hvd_engine_inspect(
                self._ptr, buf, cap))
            raw = buf.value.decode()
            records = [json.loads(line)
                       for line in raw.splitlines() if line]
            if len(records) >= total or cap >= (1 << 24):
                return records
            cap *= 2

    def _pending_names(self):
        """Names of the in-flight tensors (the quiesce report must NAME
        work like the python twin, not count it) — a projection of the
        inspect table, which superseded the bare
        ``hvd_engine_pending_names`` list."""
        return [r["name"] for r in self.inspect()]

    def poll(self, handle: int) -> bool:
        st = self._lib.hvd_engine_poll(self._ptr, handle)
        if st < 0:
            raise EngineError(f"unknown handle {handle}")
        if st == 1:
            # CLEAN completion: the C++ entry no longer references a
            # donated buffer — release the pin here too, so poll-only
            # callers don't hold donated memory until shutdown (the
            # python twin drops its reference at completion). Errored
            # completions (st == 2) keep the pin until synchronize
            # classifies them: a deadline expiry releases the waiter
            # while the entry may still read the buffer in place.
            self._donated.pop(handle, None)
        return bool(st)

    def synchronize(self, handle: int) -> np.ndarray:
        nbytes = ctypes.c_longlong()
        ndim = ctypes.c_int()
        shape8 = (ctypes.c_longlong * 8)()
        err = ctypes.create_string_buffer(256)
        rc = self._lib.hvd_engine_wait_meta(
            self._ptr, handle, ctypes.byref(nbytes), ctypes.byref(ndim),
            shape8, err)
        if rc < 0:
            raise EngineError(f"unknown handle {handle}")
        dtype, name = self._meta.pop(handle,
                                     (np.dtype(np.float32), ""))
        if rc == 1:
            self._lib.hvd_engine_drop(self._ptr, handle)
            msg = err.value.decode()
            if "exceeded its deadline" in msg:
                # The waiter was released by the deadline sweep while
                # the entry may STILL be in flight: a donated buffer
                # stays pinned forever (the wedged executor may read it
                # in place), and the expiry earns the attributed flight
                # dump (rate-limited per reason — ONE dump per expiry).
                buf = self._donated.pop(handle, None)
                if buf is not None:
                    self._parked_donations.append(buf)
                self._dump_flight(msg, kind="deadline")
                raise CollectiveTimeout(msg)
            self._donated.pop(handle, None)
            if "was cancelled" in msg:
                raise CancelledError(msg)
            if "names must be unique" in msg:
                # Deferred duplicate: a batched submit's request whose
                # name was already in flight when the loop folded the
                # ring — that handle alone failed (submit_n docstring).
                raise DuplicateNameError(msg)
            if "shut down" in msg:
                raise ShutdownError(msg)
            raise EngineError(msg)
        # Clean completion: the C++ entry no longer references a donated
        # buffer — release the pin.
        self._donated.pop(handle, None)
        # Result buffer from the pool — recycled once the caller drops
        # the returned view.
        out = self._pool.checkout(int(nbytes.value), np.uint8)
        rc = self._lib.hvd_engine_copy_result(
            self._ptr, handle, out.ctypes.data, out.nbytes)
        if rc != 0:
            raise EngineError("result copy failed")
        shape = tuple(shape8[i] for i in range(ndim.value))
        result = out.view(dtype).reshape(shape)
        # Numerics: same synchronize-time check the python engine runs —
        # identical counter names, verdict shape and halt behavior.
        numx.engine_check_result(name, result)
        return result

    def set_params(self, cycle_time_s: Optional[float] = None,
                   fusion_threshold: Optional[int] = None):
        """Live parameter updates (the autotuner drives this)."""
        if self._ptr is None:
            return
        self._maybe_activate_negotiation()
        if _multi_controller() and self._coordinator is None:
            # No negotiation available: fall back to unfused, name-ordered
            # execution (see engine.config_from_env) — and the response
            # cache follows the same rule.
            self._lib.hvd_engine_set_sort_by_name(self._ptr, 1)
            if fusion_threshold is not None:
                fusion_threshold = 0
            if self.cache_capacity:
                self.cache_capacity = 0
                record_cache_config(0, forced_off=True)
        self._lib.hvd_engine_set_params(
            self._ptr,
            -1.0 if cycle_time_s is None else float(cycle_time_s),
            -1 if fusion_threshold is None else int(fusion_threshold))
        if cycle_time_s is not None and cycle_time_s > 0:
            self.cycle_time_s = cycle_time_s
        if fusion_threshold is not None and fusion_threshold >= 0:
            self.fusion_threshold = fusion_threshold
        if self._coordinator is not None:
            # Process 0's tuned values propagate through the round params
            # (reference: ParameterManager::SyncParams).
            self._coordinator.cycle_time_s = self.cycle_time_s
            self._coordinator.fusion_threshold = self.fusion_threshold

    def current_params(self):
        """(cycle_time_s, fusion_threshold) as the C++ loop sees them —
        negotiated rounds update the native values directly, so the
        Python-side mirrors can lag."""
        if self._ptr is None:
            return self.cycle_time_s, self.fusion_threshold
        cyc = ctypes.c_double()
        fus = ctypes.c_longlong()
        self._lib.hvd_engine_get_params(
            self._ptr, ctypes.byref(cyc), ctypes.byref(fus))
        return float(cyc.value), int(fus.value)

    def abandon(self):
        """Elastic teardown of a WEDGED engine — the C++ loop thread is
        blocked inside the negotiator trampoline's KV RPC against a dead
        coordination service, so :meth:`shutdown`'s ``hvd_engine_join``
        would never return. Signal shutdown WITHOUT joining (the loop is
        parked forever — the caller parks this object so the trampolines
        stay alive) and poison the coordinator without publishing."""
        self._stall_stop.set()
        tele.REGISTRY.unregister_sync(self._collect_stats)
        if self._param_manager is not None:
            try:
                self._param_manager.close()
            except Exception:
                pass
        c = self._coordinator
        if c is not None:
            c.dead = c.dead or "engine abandoned (elastic reconfiguration)"
            c._closed = True
        # Pool hygiene: the parked C++ loop thread may still hold
        # checked-out slabs (its own pool is engine-internal and parks
        # with it); poison the python-side pool so nothing it lent can
        # be handed out again. _donated is NOT cleared — the parked
        # loop may still read those buffers forever.
        self._pool.poison()
        ptr, self._ptr = self._ptr, None
        if ptr is not None:
            self._lib.hvd_engine_shutdown(ptr)  # signal only — no join
        self._meta.clear()
        tl.uninstall_sigusr1(self._dump_sigusr1)

    def shutdown(self):
        if self._ptr is None:
            return
        self._stall_stop.set()
        # Stop the registry syncing first: it must never read through a
        # dead engine pointer.
        tele.REGISTRY.unregister_sync(self._collect_stats)
        if self._param_manager is not None:
            self._param_manager.close()
        if self._coordinator is not None:
            # Tombstone first: peers blocked mid-round on our next message
            # surface ShutdownError instead of hanging.
            self._coordinator.close()
        # Quiesce (fail outstanding work, wake waiters, join C++ threads)
        # but deliberately LEAK the small C++ object: another thread may
        # still be inside hvd_engine_wait_meta, and destroying a condition
        # variable with blocked waiters is undefined behavior.
        self._lib.hvd_engine_join(self._ptr)
        # Final telemetry fold: the loop is joined, so this captures the
        # shutdown-drain completions/errors too (parity with the python
        # twin, which counts them in _complete).
        self._collect_stats()
        self._ptr = None
        self._meta.clear()
        # Workers joined: no C++ reference to donated buffers remains.
        self._donated.clear()
        # A later SIGUSR1 must dump a LIVE engine's ring, not this dead
        # one's — and the module-global handler state must not pin us.
        tl.uninstall_sigusr1(self._dump_sigusr1)
