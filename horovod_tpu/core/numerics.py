"""Training-numerics observatory: gradient health, bf16 drift gauges and
the cross-rank state-consistency checker.

The observability stack before this module watched *time and bytes*
(telemetry PR 2, tracing PR 3, the performance sentinel PR 5) — nothing
watched *the numbers*. Mixed-precision training with master shards
(arxiv 2004.13336 §4) and the quantized-allreduce roadmap (EQuARX,
arxiv 2506.17615) are exactly the regimes where silent NaN/Inf
propagation, bf16 drift and cross-rank state divergence produce wrong
models that *look* fast. This module makes all three first-class,
attributed, observable events:

- **Gradient health** (:func:`note_step_health`): the compiled step
  computes global/per-bucket grad norms, nonfinite counts and a
  per-rank attribution vector *in-program*
  (:mod:`horovod_tpu.jax.numerics` — near-zero extra HBM traffic); the
  host feeds them here on the ``HVD_NUMERICS_EVERY`` cadence. A
  nonfinite step yields ONE ``nonfinite`` sentinel verdict + flight
  dump naming the step, the offending dtype bucket and the rank; under
  ``HVD_NUMERICS=halt`` the in-program guard has already skipped the
  poisoned update (params bitwise-unchanged) and :class:`NonfiniteError`
  is raised.
- **bf16 drift gauges** (:func:`note_drift` / :func:`note_update_ratio`):
  the automated version of docs/troubleshooting.md's manual drift
  ladder — periodic master↔resident max-ULP per dtype bucket on the
  sharded master path, and the update/param norm-ratio gauge for the
  masterless ``state_storage`` caveat.
- **Cross-rank consistency digest** (:func:`check_consistency`): at
  control-plane points every process digests its parameter buckets
  (crc32 over the raw bytes + an f64 sum + a nonfinite count), the
  digests are allgathered, and a mismatch yields an attributed
  ``diverged`` verdict naming the deviating rank(s) and bucket on EVERY
  process — the detection instrument elastic worlds (ROADMAP item 3)
  and quantized allreduce (item 1) will both stand on.

Engines: both engines call :func:`engine_note_submit` /
:func:`engine_check_result` on their python submit/synchronize
boundaries — a nonfinite reduced result triggers a one-shot cross-rank
attribution exchange (an eager allgather of each process's local
nonfinite count at submit), so every survivor's verdict names the
poisoning rank. Like ``HVD_CONSISTENCY_CHECKS``, the exchange assumes
SPMD-symmetric synchronize order across processes (the standard
collective-call contract).

Knobs: ``HVD_NUMERICS=off|warn|halt`` (default **warn**; the bench
headline sets ``off`` for its AOT window — bench.py), and
``HVD_NUMERICS_EVERY`` (host check cadence in steps, default 50; the
halt policy checks every step). Stdlib + numpy only on the observe
path; jax is imported only where a collective actually runs.

Surfaces: ``hvd.numerics_report()``, the ``hvd_numerics_*`` metric
family in every telemetry exposition (file, ``/metrics``,
``utils.stats --json``), ``/healthz`` (degrades on a recent
``nonfinite``/``diverged`` verdict), ``python -m
horovod_tpu.utils.numerics <file|http://...>``, and the ``numerics``
object in bench.py's JSON line.
"""

from __future__ import annotations

import logging
import os
import threading
import zlib
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.core import sentinel as _sentinel
from horovod_tpu.core import telemetry as tele

LOG = logging.getLogger("horovod_tpu.numerics")

_POLICIES = ("off", "warn", "halt")


class NonfiniteError(RuntimeError):
    """Raised under ``HVD_NUMERICS=halt`` when a nonfinite gradient (or
    reduced engine result) is detected. The in-program guard has already
    kept the poisoned update from being applied."""


def policy() -> str:
    """The ``HVD_NUMERICS`` policy: ``off`` (no instrumentation — the
    compiled step lowers to the identical HLO as pre-numerics builds),
    ``warn`` (observe + verdict + dump) or ``halt`` (additionally skip
    the poisoned update in-program and raise). Default ``warn``;
    unknown spellings are treated as ``warn`` with one log line, and
    ``0``/``false`` read as ``off``."""
    v = os.environ.get("HVD_NUMERICS", "warn").strip().lower()
    if v in ("0", "false", "no"):
        return "off"
    if v in ("1", "true", "on"):
        return "warn"
    if v not in _POLICIES:
        LOG.warning("HVD_NUMERICS=%r is not off|warn|halt; treating as "
                    "'warn'", v)
        return "warn"
    return v


def enabled() -> bool:
    return policy() != "off"


def check_every() -> int:
    """Host-side check cadence in steps (``HVD_NUMERICS_EVERY``, default
    50). The halt policy always checks every step — a detection delayed
    by the cadence could not raise before the NEXT poisoned update."""
    try:
        return max(1, int(os.environ.get("HVD_NUMERICS_EVERY", "") or 50))
    except ValueError:
        return 50


# ---------------------------------------------------------------------------
# State: fire-once latches + last reports (one per process)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_fired: Dict[str, dict] = {}      # verdict kind -> first verdict
_last_drift: Optional[dict] = None
_last_consistency: Optional[dict] = None
_engine_submit_nf: Dict[str, int] = {}  # tensor name -> local nf count
_ENGINE_SUBMIT_MAX = 1024
# One-shot latch for the engine attribution allgather, SEPARATE from
# the _fired verdict latch: _fired can be set asymmetrically across
# processes (a process-local Trainer verdict), and a collective gated
# on an asymmetric flag is a distributed hang. This flag flips only
# inside engine_check_result, whose entry is SPMD-symmetric (identical
# reduced results, identical synchronize order — the documented engine
# contract), so every process takes the exchange branch together.
_engine_attr_done = False


def reset():
    """Drop the latches and reports (tests only)."""
    global _last_drift, _last_consistency, _engine_attr_done
    with _lock:
        _fired.clear()
        _engine_submit_nf.clear()
        _last_drift = None
        _last_consistency = None
        _engine_attr_done = False


def _fire(kind: str, info: dict) -> dict:
    """One attributed verdict + flight dump per verdict kind per process
    (the sentinel's dump layer additionally rate-limits repeats of the
    same reason); later events of the same kind only count."""
    with _lock:
        first = kind not in _fired
        if first:
            _fired[kind] = info
    tele.REGISTRY.counter(f"numerics.{kind}.events").inc()
    if first:
        return _sentinel.note_numerics(kind, info)
    return dict(info, verdict=kind, dump=None, suppressed=True)


# ---------------------------------------------------------------------------
# Gradient health intake (the compiled path lands here via the Trainer)
# ---------------------------------------------------------------------------


def note_step_health(health: dict, step: Optional[int] = None,
                     origin: str = "trainer"):
    """One step's in-program health stats, already fetched to host
    (plain numbers / 0-d numpy). Feeds the telemetry rings and gauges;
    fires the ``nonfinite`` verdict (first offender: step, bucket, rank)
    and — under the ``halt`` policy — raises :class:`NonfiniteError`
    AFTER the dump landed. Never mutates training state: the in-program
    guard already kept the update from applying."""
    if not health:
        return None
    tele.REGISTRY.counter("numerics.steps.checked").inc()
    gn = health.get("grad_norm")
    if gn is not None:
        gn = float(gn)
        tele.REGISTRY.ring("numerics.grad_norm").push(gn)
    buckets = health.get("buckets") or {}
    for k, b in buckets.items():
        tele.REGISTRY.gauge(f"numerics.grad_norm.{k}").set(
            float(b["norm"]))
    if "update_norm" in health and "param_norm" in health:
        note_update_ratio(float(health["update_norm"]),
                          float(health["param_norm"]))
    nf_total = int(health.get("nonfinite") or 0)
    bad_buckets = {k: int(b["nonfinite"]) for k, b in buckets.items()
                   if int(b["nonfinite"])}
    if not nf_total and not bad_buckets:
        return None
    tele.REGISTRY.counter("numerics.nonfinite.steps").inc()
    tele.REGISTRY.counter("numerics.nonfinite.values").inc(
        max(nf_total, sum(bad_buckets.values())))
    ranks: List[int] = []
    per_rank = health.get("per_rank_nonfinite")
    if per_rank is not None:
        arr = np.asarray(per_rank).reshape(-1)
        ranks = [int(r) for r in np.nonzero(arr)[0]]
    info = {
        "origin": origin,
        "step": int(step) if step is not None else None,
        "grad_norm": gn,
        "nonfinite": nf_total,
        "buckets": bad_buckets,
        "ranks": ranks or None,
    }
    verdict = _fire("nonfinite", info)
    if policy() == "halt":
        raise NonfiniteError(
            f"nonfinite gradients at step {info['step']}: "
            f"{nf_total} value(s) in bucket(s) "
            f"{sorted(bad_buckets) or '?'}"
            + (f" from rank(s) {ranks}" if ranks else "")
            + " — the poisoned update was NOT applied "
              "(HVD_NUMERICS=halt)")
    return verdict


# ---------------------------------------------------------------------------
# Drift gauges (bf16 resident state — the automated troubleshooting ladder)
# ---------------------------------------------------------------------------


def note_drift(ulp_by_bucket: Dict[str, int], step: Optional[int] = None):
    """Periodic master↔resident divergence, as max ULP per dtype bucket
    (:func:`horovod_tpu.jax.sharded.drift_ulp` computes it). The
    re-anchored sharded path should read ≤1; growth means the policy is
    not applied where you think (docs/troubleshooting.md)."""
    global _last_drift
    tele.REGISTRY.counter("numerics.drift.checks").inc()
    for k, u in ulp_by_bucket.items():
        tele.REGISTRY.gauge(f"numerics.drift_ulp.{k}").set(int(u))
    with _lock:
        _last_drift = {"step": step,
                       "ulp": {k: int(u) for k, u in
                               ulp_by_bucket.items()}}


def note_update_ratio(update_norm: float, param_norm: float):
    """The masterless-path gauge (``fused.state_storage`` caveat): the
    ||update||/||params|| ratio. Sustained ratios below ~1 bf16 ulp
    (~0.4 %) of the weights mean updates are being rounded away —
    exactly the late-training drift regime the troubleshooting ladder
    diagnoses by hand."""
    tele.REGISTRY.gauge("numerics.update_norm").set(update_norm)
    tele.REGISTRY.gauge("numerics.param_norm").set(param_norm)
    if param_norm > 0:
        tele.REGISTRY.gauge("numerics.update_ratio").set(
            update_norm / param_norm)


# ---------------------------------------------------------------------------
# Cross-rank consistency digest
# ---------------------------------------------------------------------------


#: Entries per bucket digest row: [crc_hi16, crc_lo16, sum, nonfinite].
#: The crc32 ships as two 16-bit halves because the wire is f32 (the
#: eager allgather runs without x64): a whole 32-bit crc would round to
#: ~24 bits of mantissa and a near-collision divergence could vanish in
#: transit. 16-bit halves are exact in f32 at any value.
DIGEST_WIDTH = 4


def params_digest(tree) -> Dict[str, np.ndarray]:
    """Per-dtype-bucket digest of a parameter pytree: ``[crc32 high
    half, crc32 low half, sum, nonfinite count]``. The crc makes ANY
    bitwise difference visible; the sum/count give a human a direction.
    Host math only — identical inputs digest identically on every
    process."""
    from horovod_tpu.ops import collectives as _C

    buckets: Dict[str, List[np.ndarray]] = {}
    import jax as _jax

    for leaf in _jax.tree_util.tree_leaves(tree):
        arr = _C.fetch(leaf) if hasattr(leaf, "dtype") else np.asarray(leaf)
        buckets.setdefault(np.asarray(arr).dtype.name, []).append(
            np.asarray(arr))
    out = {}
    for k in sorted(buckets):
        crc = 0
        total = 0.0
        nf = 0
        for a in buckets[k]:
            crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
            af = a.astype(np.float64, copy=False) \
                if np.issubdtype(a.dtype, np.floating) else a
            if np.issubdtype(a.dtype, np.floating):
                fin = np.isfinite(af)
                total += float(af[fin].sum())
                nf += int(a.size - fin.sum())
            else:
                total += float(np.asarray(af, np.float64).sum())
        # The f32-rounded sum stays deterministic (identical f64 in →
        # identical f32 out) — it is the human-direction field; the crc
        # halves are the exact divergence detector.
        out[k] = np.asarray([float(crc >> 16), float(crc & 0xFFFF),
                             np.float32(total), float(nf)], np.float64)
    return out


def compare_digests(gathered: np.ndarray, bucket_names: List[str],
                    local_size: int) -> dict:
    """Pure comparison (unit-testable without a world): ``gathered`` is
    the (world, nbuckets, DIGEST_WIDTH) matrix of every chip's process
    digest. A STRICT majority digest wins and the deviating chips are
    mapped to controller processes by the contiguous local-block rule.
    Without a strict majority (the 2-process 4-vs-4 tie: each process's
    digest is replicated across its local chips, so a two-controller
    disagreement can never out-vote itself) the divergence is real but
    unattributable by vote — EVERY rank is reported and the report is
    marked ``ambiguous`` rather than letting dict-insertion order crown
    rank 0's digest and blame the possibly-healthy other side. Identical
    input → identical report on every process."""
    world = gathered.shape[0]
    mismatch: Dict[str, List[int]] = {}
    ambiguous = False
    for bi, name in enumerate(bucket_names):
        rows = [tuple(gathered[r, bi]) for r in range(world)]
        counts: Dict[tuple, int] = {}
        for t in rows:
            counts[t] = counts.get(t, 0) + 1
        if len(counts) == 1:
            continue
        best = max(counts.values())
        leaders = [t for t, c in counts.items() if c == best]
        if len(leaders) == 1 and best * 2 > world:
            majority = leaders[0]
            mismatch[name] = [r for r, t in enumerate(rows)
                              if t != majority]
        else:
            mismatch[name] = list(range(world))
            ambiguous = True
    report = {"ok": not mismatch, "buckets": list(bucket_names),
              "world": world}
    if mismatch:
        ranks = sorted({r for rs in mismatch.values() for r in rs})
        report["mismatch"] = {k: v for k, v in mismatch.items()}
        report["ranks"] = ranks
        report["processes"] = sorted({r // max(1, local_size)
                                      for r in ranks})
        if ambiguous:
            report["ambiguous"] = True
    return report


def check_consistency(tree, tag: str = "params",
                      step: Optional[int] = None) -> dict:
    """Allreduce-compare a cheap per-bucket parameter digest across the
    world (an eager allgather — call from a control-plane point, in
    lockstep on every process). A mismatch yields an attributed
    ``diverged`` verdict + flight dump on EVERY process, naming the
    deviating rank(s) and bucket. Returns the report dict."""
    global _last_consistency
    import jax.numpy as jnp

    from horovod_tpu.common import topology as _topo
    from horovod_tpu.ops import collectives as _C

    st = _topo._require_init()
    tele.REGISTRY.counter("numerics.consistency.checks").inc()
    digest = params_digest(tree)
    names = sorted(digest)
    local = np.stack([digest[k] for k in names]) if names else \
        np.zeros((0, DIGEST_WIDTH), np.float64)
    if st.size == 1 or not names:
        report = {"ok": True, "buckets": names, "world": st.size}
    else:
        gathered = np.asarray(_C.allgather(
            jnp.asarray(local.reshape(1, -1))))
        gathered = gathered.reshape(st.size, len(names), DIGEST_WIDTH)
        report = compare_digests(gathered, names, st.local_size)
    report["tag"] = tag
    if step is not None:
        report["step"] = step
    with _lock:
        _last_consistency = report
    if not report["ok"]:
        tele.REGISTRY.counter("numerics.consistency.mismatches").inc()
        info = {"origin": "numerics.consistency", "tag": tag,
                "step": step,
                "buckets": sorted(report["mismatch"]),
                "ranks": report["ranks"],
                "processes": report["processes"]}
        _fire("diverged", info)
    return report


# ---------------------------------------------------------------------------
# Engine hooks (both engines' python submit/synchronize boundaries)
# ---------------------------------------------------------------------------


def np_nonfinite(tensor) -> int:
    try:
        t = np.asarray(tensor)
        if t.dtype.kind == "f":  # the common case, sans issubdtype cost
            return int((~np.isfinite(t)).sum())
        if not np.issubdtype(t.dtype, np.floating):
            try:  # ml_dtypes (bfloat16) are floating but not np.floating
                t = t.astype(np.float32)
            except (TypeError, ValueError):
                return 0
        return int((~np.isfinite(t)).sum())
    except Exception:  # pragma: no cover - defensive
        return 0


def engine_note_submit(name: str, tensor):
    """Called by both engines at ``*_async`` submit (on the snapshot):
    records this process's local nonfinite count per tensor name — the
    attribution side of :func:`engine_check_result`'s exchange."""
    if not enabled():
        return
    nf = np_nonfinite(tensor)
    if nf:
        tele.REGISTRY.counter("numerics.engine.nonfinite_submits").inc()
    with _lock:
        while len(_engine_submit_nf) >= _ENGINE_SUBMIT_MAX:
            _engine_submit_nf.pop(next(iter(_engine_submit_nf)))
        _engine_submit_nf[name] = nf


def engine_note_submit_batch(names, tensors):
    """The batched-submit twin of :func:`engine_note_submit` — identical
    per-tensor semantics (same counter, same attribution dict), but the
    policy/env gate, counter feed and latch lock are paid ONCE per
    batch, not once per member: a 10k-member ``submit_n`` must not
    spend more time in instrumentation wrappers than in the submit
    itself (measured: the per-call form cost ~22 us/tensor, most of it
    env reads and lock churn)."""
    if not enabled():
        return
    counts = [np_nonfinite(t) for t in tensors]
    bad = sum(1 for nf in counts if nf)
    if bad:
        tele.REGISTRY.counter("numerics.engine.nonfinite_submits").inc(bad)
    with _lock:
        for name, nf in zip(names, counts):
            while len(_engine_submit_nf) >= _ENGINE_SUBMIT_MAX:
                _engine_submit_nf.pop(next(iter(_engine_submit_nf)))
            _engine_submit_nf[name] = nf


def engine_check_result(name: str, result):
    """Called by both engines in ``synchronize``: a nonfinite reduced
    result fires the one-shot attribution exchange — every process
    allgathers its local-at-submit nonfinite count, so every survivor's
    ``nonfinite`` verdict names the poisoning process. Raises
    :class:`NonfiniteError` under the halt policy. Identical counter
    names and verdict shape on both engines (this IS the shared code)."""
    if not enabled():
        return
    nf = np_nonfinite(result)
    if not nf:
        return
    global _engine_attr_done
    tele.REGISTRY.counter("numerics.engine.nonfinite_results").inc()
    with _lock:
        local = _engine_submit_nf.get(name, 0)
        first_exchange = not _engine_attr_done
        _engine_attr_done = True
    processes = None
    if first_exchange:
        # One-shot exchange, gated on ITS OWN latch (not _fired, which a
        # process-local Trainer verdict can set asymmetrically — see the
        # latch comment above): all processes synchronize the same
        # reduced (identically nonfinite) tensor, so all enter here
        # together — the same SPMD-symmetry contract
        # HVD_CONSISTENCY_CHECKS documents. Best-effort: a world where
        # the eager path is unavailable still gets the local-knowledge
        # verdict.
        try:
            import jax.numpy as jnp

            from horovod_tpu.common import topology as _topo
            from horovod_tpu.ops import collectives as _C

            st = _topo._require_init()
            flags = np.asarray(_C.allgather(
                jnp.asarray([[np.int32(local)]])))
            flags = flags.reshape(-1)
            processes = sorted({int(r) // max(1, st.local_size)
                                for r in np.nonzero(flags)[0]})
        except Exception as exc:  # pragma: no cover - defensive
            LOG.warning("nonfinite attribution exchange unavailable: %s",
                        exc)
    info = {"origin": "engine", "tensor": name, "nonfinite": nf,
            "local_nonfinite_at_submit": local,
            "processes": processes}
    _fire("nonfinite", info)
    if policy() == "halt":
        raise NonfiniteError(
            f"nonfinite reduced result for '{name}' ({nf} value(s))"
            + (f" from process(es) {processes}" if processes else "")
            + " (HVD_NUMERICS=halt)")


def note_eager_nonfinite(op: str, count: int):
    """Eager-collective input carried nonfinite values (the collectives
    layer feeds this when the policy is on) — a counter, not a verdict:
    metric averaging has its own masking (utils/metrics.py)."""
    if count:
        tele.REGISTRY.counter(f"numerics.eager.{op}.nonfinite").inc(count)


# ---------------------------------------------------------------------------
# Report surfaces
# ---------------------------------------------------------------------------


def report() -> dict:
    """The ``hvd.numerics_report()`` surface: policy + the current state
    of every numerics gauge/counter family + the last drift/consistency
    reports and first verdicts."""
    flat = tele.REGISTRY.flat()
    num = {k: v for k, v in flat.items() if k.startswith("numerics.")}
    with _lock:
        fired = {k: dict(v) for k, v in _fired.items()}
        drift = dict(_last_drift) if _last_drift else None
        consistency = dict(_last_consistency) if _last_consistency \
            else None
    return {
        "policy": policy(),
        "check_every": check_every(),
        "metrics": num,
        "verdicts": fired or None,
        "drift": drift,
        "consistency": consistency,
    }


def compact() -> dict:
    """Small summary for bench.py's one JSON line (post-window; nulls
    when nothing was observed)."""
    flat = tele.REGISTRY.flat()
    ring = flat.get("numerics.grad_norm") or {}
    with _lock:
        consistency_ok = (None if _last_consistency is None
                          else bool(_last_consistency["ok"]))
        fired = sorted(_fired) or None
    return {
        "policy": policy(),
        "steps_checked": flat.get("numerics.steps.checked") or None,
        "nonfinite_steps": flat.get("numerics.nonfinite.steps") or None,
        "grad_norm_last": ring.get("last"),
        "consistency_ok": consistency_ok,
        "verdicts": fired,
    }


def summary() -> dict:
    """The sentinel /healthz payload's ``numerics`` section."""
    with _lock:
        return {
            "policy": policy(),
            "verdicts": sorted(_fired) or None,
            "drift": dict(_last_drift) if _last_drift else None,
            "consistency_ok": (None if _last_consistency is None
                               else bool(_last_consistency["ok"])),
        }
