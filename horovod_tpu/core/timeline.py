"""Chrome-tracing timeline (reference: horovod/common/timeline.{h,cc} —
same phase vocabulary, same per-tensor lanes, same HOROVOD_TIMELINE
activation; device-side spans come from the XLA profiler instead of CUDA
events).

Distributed-tracing extensions beyond the reference:

- ``HVD_TIMELINE=<dir>`` writes ONE trace per controller process
  (``timeline.rank{N}.json``); each trace embeds an ``HVD_CLOCK``
  metadata event mapping its timeline clock onto a common time base
  (see :meth:`Timeline.clock_sync` and utils/trace.py ``merge``). The
  single-file spelling (``HVD_TIMELINE=/path/trace.json``) still works
  and records exactly the reference's rank-local view.
- An always-on **flight recorder**: a bounded in-memory ring of the most
  recent events, recorded whether or not a trace file is being written
  (the C++ engine keeps its own ring — hvdcore.cc — exported through
  ``hvd_engine_recent_events`` with the same event shape). The engines
  dump it (with a telemetry snapshot) on stalls, failed negotiations,
  shutdown-drained work and SIGUSR1, so a hung or dying run yields a
  post-mortem trace without any env var set.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import tempfile
import threading
import time
from collections import deque
from typing import Callable, List, Optional

# Activity names (reference: operations.h:29-50).
QUEUE = "QUEUE"
# Submit-time snapshot copy (nested at the head of the QUEUE span; its
# END args carry the zero-copy attribution: {"pooled": bool} for a
# pool-slab copy, {"donated": true} for an ownership handoff that
# skipped the copy entirely — utils/trace.py splits MEMCPY medians by
# these the way NEGOTIATE is split by `cached`).
MEMCPY = "MEMCPY"
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
# Instant mark inside a NEGOTIATE_* span: process N announced the tensor
# (reference: the per-rank readiness events timeline.cc:106-130 records
# while a tensor is NEGOTIATING — the trace then shows who was late).
RANK_READY = "RANK_READY"
# A cooperatively-cancelled collective: pre-announce entries retire
# locally under this span; post-agreement entries complete cross-rank
# (a fused batch cannot be torn) and the span marks the discarded
# result. Both engines' writers spell it (hvdcheck parity-spans).
CANCELLED = "CANCELLED"
# Instant stamped when a per-request deadline fires: args carry the
# phase the entry was stuck in (QUEUE/NEGOTIATE/ALLREDUCE/...) and its
# age — the attribution the CollectiveTimeout error repeats.
DEADLINE_EXCEEDED = "DEADLINE_EXCEEDED"
# Clock metadata event: maps this trace's timeline clock onto the common
# time base (utils/trace.py merge). args: rank, epoch_wall_us (wall-clock
# µs at trace ts 0), offset_us (subtract from epoch_wall_us+ts to land on
# the common base — the wall↔monotonic bridge, replaced by rank 0's
# bridge once the coordinator's anchor exchange completes), rtt_us (the
# measured KV round trip bounding the exchange's error).
CLOCK_SYNC = "HVD_CLOCK"

_FLUSH_INTERVAL_S = 1.0  # reference: timeline.h:32


def flight_recorder_size() -> int:
    try:
        return max(16, int(os.environ.get("HVD_FLIGHT_RECORDER_SIZE", "512")))
    except ValueError:
        return 512


def _process_index() -> int:
    """This controller's process index, resolvable before hvd.init():
    topology when initialized, else the launcher's HVD_PROCESS_ID."""
    try:
        from horovod_tpu.common import topology as topo

        if topo.is_initialized():
            return topo.process_index()
    except Exception:
        pass
    try:
        return int(os.environ.get("HVD_PROCESS_ID", "0"))
    except ValueError:
        return 0


class Timeline:
    """Per-process chrome://tracing JSON writer. One "pid" lane per tensor
    name (reference: timeline.cc:60-96 metadata events). The clock base
    and the flight-recorder ring are live even with no file (path=None):
    ``now_us`` always returns the real clock and ``recent()`` always holds
    the last-N events."""

    def __init__(self, path: Optional[str], rank: Optional[int] = None):
        self._path = path
        self._lock = threading.RLock()
        self._fh = None
        self._pids = {}
        self._last_flush = 0.0
        self._first = True
        # The clock base is captured unconditionally: a disabled timeline
        # must still answer now_us() with the real clock (callers compute
        # retro-span boundaries from it) and stamp ring events.
        wall = time.time()
        self._start = time.monotonic()
        self.rank = _process_index() if rank is None else rank
        # Wall-clock µs corresponding to trace ts 0, and the wall↔
        # monotonic bridge: epoch_wall_us + ts - offset_us lands every
        # same-host rank on the shared CLOCK_MONOTONIC base. clock_sync
        # replaces offset_us with rank 0's bridge (exchanged through the
        # KV store) so multi-host traces merge on rank 0's frame too.
        self.epoch_wall_us = int(wall * 1e6)
        self.offset_us = int((wall - self._start) * 1e6)
        self.rtt_us: Optional[int] = None
        self._ring: deque = deque(maxlen=flight_recorder_size())
        # Metadata (the HVD_CLOCK mapping) is pinned in its own tiny ring
        # so a busy run's span events can never evict it — every flight
        # dump must carry the clock mapping or cross-rank alignment of
        # dumps silently degrades to local time.
        self._meta_ring: deque = deque(maxlen=16)
        if path:
            self._fh = open(path, "w")
            self._fh.write("[\n")
            # Crash-safety: a killed run leaves a truncated file. Events
            # are separator-FIRST (no trailing comma after the last one),
            # which the chrome/Perfetto JSON-array reader accepts without
            # the closing ']'; a clean interpreter exit that never reached
            # close() (engine leaked, Ctrl-C mid-run) is closed here.
            atexit.register(self.close)
        # Recorded in the ring even with no file (the C++ twin does the
        # same), so flight-recorder dumps carry the clock mapping too.
        self._emit_clock_meta()

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def _ts_us(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _pid(self, name: str) -> int:
        if name not in self._pids:
            pid = len(self._pids) + 1
            self._pids[name] = pid
            self._emit(
                {"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": name}}
            )
        return self._pids[name]

    def _emit(self, ev: dict):
        # Separator BEFORE each event (after the first): however the
        # process dies, the file never ends in a trailing comma, so it
        # stays loadable in Perfetto after truncation.
        sep = "" if self._first else ",\n"
        self._first = False
        self._fh.write(sep + json.dumps(ev))
        now = time.monotonic()
        if now - self._last_flush > _FLUSH_INTERVAL_S:
            self._fh.flush()
            self._last_flush = now

    def now_us(self) -> int:
        """Current timeline clock, for retro-emitted spans (a caller that
        learns a phase boundary only after the fact — e.g. WAIT_FOR_DATA
        split out of an executor round-trip — records explicit ts). Valid
        whether or not a file is being written: the base is captured at
        construction, so a timeline enabled mid-run never receives a
        zero/negative retro timestamp."""
        return self._ts_us()

    def _clock_args(self) -> dict:
        args = {"rank": self.rank, "epoch_wall_us": self.epoch_wall_us,
                "offset_us": self.offset_us}
        if self.rtt_us is not None:
            args["rtt_us"] = self.rtt_us
        return args

    def _emit_clock_meta(self):
        args = self._clock_args()
        with self._lock:
            self._meta_ring.append({"name": CLOCK_SYNC, "ph": "M",
                                    "ts": self._ts_us(), "args": args})
            if self._fh is not None:
                self._emit({"name": CLOCK_SYNC, "ph": "M", "pid": 0,
                            "args": args})

    def clock_sync(self, offset_us: int, rtt_us: Optional[int]):
        """Record the coordinator's clock-anchor exchange result: rank 0's
        wall↔monotonic bridge (the common-base offset every rank now
        shares) plus the measured KV round trip that bounds the estimate's
        error. Re-emits the HVD_CLOCK metadata; the merge tool uses the
        LAST one per trace."""
        self.offset_us = int(offset_us)
        self.rtt_us = None if rtt_us is None else int(rtt_us)
        self._emit_clock_meta()

    def _event(self, phase: str, tensor: str, activity: str,
               args: Optional[dict], ts_us: Optional[int] = None):
        ts = self._ts_us() if ts_us is None else ts_us
        rec = {"name": activity, "ph": phase, "ts": ts, "tensor": tensor}
        if args:
            rec["args"] = args
        with self._lock:
            # Flight recorder: always on, bounded, never touches disk.
            self._ring.append(rec)
            if self._fh is None:  # no file (disabled, or closed)
                return
            ev = {"name": activity, "ph": phase, "pid": self._pid(tensor),
                  "ts": ts}
            if phase == "i":
                ev["s"] = "p"  # instant scope: process
            if args:
                ev["args"] = args
            self._emit(ev)

    def start(self, tensor: str, activity: str, args: Optional[dict] = None,
              ts_us: Optional[int] = None):
        self._event("B", tensor, activity, args, ts_us)

    def end(self, tensor: str, activity: str, args: Optional[dict] = None,
            ts_us: Optional[int] = None):
        self._event("E", tensor, activity, args, ts_us)

    def instant(self, tensor: str, activity: str,
                args: Optional[dict] = None):
        """Zero-duration mark on the tensor's lane (chrome 'i' event) —
        e.g. RANK_READY instants inside a NEGOTIATE_* span."""
        self._event("i", tensor, activity, args)

    def recent(self) -> List[dict]:
        """The flight-recorder ring: the most recent events (bounded by
        HVD_FLIGHT_RECORDER_SIZE), each ``{"name", "ph", "ts", "tensor",
        "args"?}`` — the same shape the C++ engine's ring exports. The
        pinned metadata (HVD_CLOCK, newest last) leads the list so the
        clock mapping survives however many span events followed it."""
        with self._lock:
            return ([dict(ev) for ev in self._meta_ring]
                    + [dict(ev) for ev in self._ring])

    def close(self):
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:  # raced with another closer
                return
            self._fh.write("\n]\n")
            self._fh.close()
            self._fh = None
        # Drop the crash-safety hook: without this, every engine
        # generation's closed Timeline (and its per-tensor lane map)
        # stays pinned by the atexit registry for process lifetime.
        atexit.unregister(self.close)


def timeline_path_from_env() -> Optional[str]:
    """HOROVOD_TIMELINE=<file-or-dir> activation (reference:
    operations.cc:1732-1736); HVD_TIMELINE is the native spelling. A
    directory target (anything not ending in ``.json``, or an existing
    directory) resolves to one file per process inside it."""
    raw = os.environ.get("HVD_TIMELINE") or os.environ.get("HOROVOD_TIMELINE")
    if not raw:
        return None
    return resolve_timeline_path(raw)


def is_dir_mode(raw: str) -> bool:
    """True when an HVD_TIMELINE value means per-rank-traces-in-a-dir
    (an existing directory, or a not-yet-existing path without a
    ``.json`` suffix). An existing plain FILE is always file mode —
    the reference allowed arbitrary trace filenames, and treating a
    legacy ``HOROVOD_TIMELINE=/tmp/hvd.trace`` leftover as a directory
    would crash engine init on makedirs. The ONE definition of the
    rule — the launcher and bench.py classify through this too, so
    where children write always matches where the mergers look."""
    if os.path.isdir(raw):
        return True
    if os.path.isfile(raw):
        return False
    return not raw.endswith(".json")


def resolve_timeline_path(raw: str, rank: Optional[int] = None) -> str:
    """Map the HVD_TIMELINE value to this process's trace file. Dir mode
    (the distributed-tracing default) creates the directory and returns
    ``<dir>/timeline.rank{N}.json``; a ``.json`` path is used verbatim
    (the reference's single-file spelling)."""
    if not is_dir_mode(raw):
        return raw
    rank = _process_index() if rank is None else rank
    os.makedirs(raw, exist_ok=True)
    return os.path.join(raw, f"timeline.rank{rank}.json")


def from_env() -> Timeline:
    return Timeline(timeline_path_from_env())


# ---------------------------------------------------------------------------
# Flight-recorder dumps (post-mortem traces for hung or dying runs)
# ---------------------------------------------------------------------------


def flight_recorder_dir() -> str:
    return (os.environ.get("HVD_FLIGHT_DIR")
            or tempfile.gettempdir())


def flight_keep() -> int:
    """Retention cap: how many dump files to keep per rank in the flight
    dir (``HVD_FLIGHT_KEEP``, default 8). A long run with repeated
    stalls/anomalies must not fill the disk with post-mortems."""
    try:
        return max(1, int(os.environ.get("HVD_FLIGHT_KEEP", "8")))
    except ValueError:
        return 8


def _prune_flight_dumps(directory: str, rank: int, keep: int):
    """Drop the oldest of THIS PROCESS's dumps beyond ``keep``
    (newest-by-mtime survive). Keyed on (rank, pid), not rank alone:
    two unrelated runs sharing the default temp dir are both rank 0,
    and one run's dump churn must never destroy the other's
    post-mortems. Best-effort: pruning must never take the dumper
    down."""
    import glob

    try:
        files = glob.glob(os.path.join(
            directory, f"hvd_flight.rank{rank}.{os.getpid()}.*.json"))
        if len(files) <= keep:
            return
        files.sort(key=lambda f: (os.path.getmtime(f), f))
        for stale in files[:-keep]:
            try:
                os.unlink(stale)
            except OSError:
                pass
    except OSError:
        pass


def dump_flight_recorder(events: List[dict], reason: str,
                         rank: Optional[int] = None,
                         path: Optional[str] = None,
                         kind: Optional[str] = None,
                         inspect: Optional[List[dict]] = None,
                         verdict: Optional[dict] = None) -> Optional[str]:
    """Write a post-mortem dump: the flight-recorder events plus a
    telemetry snapshot (counters + the straggler report — the same data
    ``hvd.telemetry()`` serves). Hang-class dumps additionally carry the
    dump ``kind`` ("stall", "deadline", "negotiation", "sigusr1"), the
    engine's per-entry ``inspect`` table, and — when the hang doctor
    reached a diagnosis — its attributed ``doctor`` verdict, making each
    dump a self-contained offline-diagnosable artifact
    (``stats --doctor <dir>``). Written atomically (tmp + replace) so a
    concurrent reader never sees a torn file. Returns the path, or None
    when writing failed (dumping must never take the caller down)."""
    rank = _process_index() if rank is None else rank
    payload = {
        "reason": str(reason),
        "rank": rank,
        "pid": os.getpid(),
        "wall_us": int(time.time() * 1e6),
        "events": list(events),
    }
    if kind is not None:
        payload["kind"] = str(kind)
    if inspect is not None:
        payload["inspect"] = list(inspect)
    if verdict is not None:
        payload["doctor"] = verdict
    try:
        from horovod_tpu.core import telemetry as tele

        payload["telemetry"] = tele.compact()
        payload["straggler"] = tele.STRAGGLERS.snapshot()
        payload["report"] = tele.report()
    except Exception:
        pass  # telemetry is additive; the events are the dump's core
    try:
        # Injected faults (core/faultline.py): every post-mortem says
        # whether the failure it records was provoked — a chaos run's
        # dumps must never read as organic incidents.
        from horovod_tpu.core import faultline as _flt

        if _flt.armed() or _flt.snapshot():
            payload["faults"] = {"spec": _flt.active_spec(),
                                 "injected": _flt.snapshot()}
    except Exception:
        pass
    prune_dir = None
    if path is None:
        # Unique per dump (wall-µs suffix) so a run's post-mortem HISTORY
        # survives — the retention cap below keeps it bounded. The older
        # {rank}.{pid} two-part spelling is still matched by every
        # consumer (they glob rank{N}.*).
        prune_dir = flight_recorder_dir()
        path = os.path.join(
            prune_dir,
            f"hvd_flight.rank{rank}.{os.getpid()}."
            f"{payload['wall_us']}.json")
    tmp = f"{path}.tmp"
    try:
        if prune_dir is not None:
            # An operator-set HVD_FLIGHT_DIR need not pre-exist: a lost
            # post-mortem is far worse than a mkdir on the dump path.
            os.makedirs(prune_dir, exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
        if prune_dir is not None:
            _prune_flight_dumps(prune_dir, rank, flight_keep())
        return path
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


_dump_rate_lock = threading.Lock()
_last_dump_at: dict = {}  # (rank, kind, reason head) -> monotonic s


def _dump_min_interval_s() -> float:
    try:
        return float(os.environ.get("HVD_FLIGHT_MIN_INTERVAL", "1.0"))
    except ValueError:
        return 1.0


def dump_and_warn(events: List[dict], reason: str, rank: Optional[int],
                  logger, kind: Optional[str] = None,
                  inspect: Optional[List[dict]] = None,
                  verdict: Optional[dict] = None) -> Optional[str]:
    """The engines' shared dump wrapper (their post-mortem semantics
    must stay twins): write the flight dump, warn with the path, never
    raise. Returns the path or None.

    Rate-limited per (rank, kind, reason): a poisoned negotiation
    re-raises the SAME failure every ~5 ms engine cycle — dumping each
    one is a 200 Hz dump storm that churns the retention cap out from
    under a concurrent reader. The dump ``kind`` is part of the key so a
    prior unrelated dump (say a shutdown drain whose reason head
    collides) can never suppress a hang post-mortem. The first dump of
    each distinct (kind, reason) always lands; repeats within
    ``HVD_FLIGHT_MIN_INTERVAL`` seconds (default 1.0; 0 disables the
    limit) are dropped."""
    try:
        min_s = _dump_min_interval_s()
        key = (rank, kind or "", str(reason).splitlines()[0][:80])
        now = time.monotonic()
        with _dump_rate_lock:
            last = _last_dump_at.get(key)
            if last is not None and min_s > 0 and now - last < min_s:
                return None
        path = dump_flight_recorder(events, reason, rank=rank, kind=kind,
                                    inspect=inspect, verdict=verdict)
        if path:
            # Stamp only on SUCCESS: a transiently unwritable flight dir
            # must not suppress the retries — "the first dump of each
            # distinct reason always lands" includes landing late.
            with _dump_rate_lock:
                while len(_last_dump_at) >= 256:  # bounded memory
                    _last_dump_at.pop(next(iter(_last_dump_at)))
                _last_dump_at[key] = now
            logger.warning("flight recorder dumped to %s (%s)", path,
                           str(reason).splitlines()[0][:200])
        return path
    except Exception:
        return None


_sigusr1_lock = threading.Lock()
_sigusr1_dump: Optional[Callable[[str], None]] = None
_sigusr1_installed = False
_sigusr1_prev = None  # the application's handler, chained after ours


def install_sigusr1(dump_fn: Callable[[str], None]):
    """Register ``dump_fn("SIGUSR1")`` to run on SIGUSR1 (the live-engine
    post-mortem hook: ``kill -USR1 <pid>`` dumps the flight recorder of a
    hung run with no env var set). The latest registrant wins — each
    engine generation re-registers its own dumper. A handler the
    application installed first is preserved and chained after the dump
    (e.g. SLURM preemption checkpointing must keep working). Installable
    only from the main thread (the signal module's rule); elsewhere the
    request is recorded but the handler of a previous main-thread install
    serves it."""
    global _sigusr1_dump, _sigusr1_installed, _sigusr1_prev
    with _sigusr1_lock:
        _sigusr1_dump = dump_fn
        if _sigusr1_installed:
            return
        try:
            _sigusr1_prev = signal.signal(signal.SIGUSR1, _on_sigusr1)
            _sigusr1_installed = True
        except (ValueError, AttributeError, OSError):
            pass  # non-main thread, or a platform without SIGUSR1


def uninstall_sigusr1(dump_fn: Callable[[str], None]):
    """Drop ``dump_fn`` if it is the current SIGUSR1 dumper (engine
    shutdown calls this): the module global must not keep a strong
    reference pinning a dead engine — and a later SIGUSR1 must not dump
    a shut-down engine's stale ring as if it were live state. A newer
    registrant is left untouched."""
    global _sigusr1_dump
    with _sigusr1_lock:
        # == not `is`: each `self._dump_flight` access builds a fresh
        # bound-method object; equality compares (__self__, __func__).
        if _sigusr1_dump == dump_fn:
            _sigusr1_dump = None


def _on_sigusr1(signum, frame):
    fn = _sigusr1_dump
    if fn is not None:
        try:
            # Hand off to a thread: the handler interrupts the main
            # thread at an arbitrary bytecode boundary, possibly INSIDE a
            # telemetry or timeline critical section — dumping inline
            # would deadlock on the non-reentrant lock the interrupted
            # frame still holds. A separate thread simply waits its turn.
            threading.Thread(target=_safe_dump, args=(fn,),
                             name="hvd-sigusr1-dump", daemon=True).start()
        except Exception:
            pass  # a signal handler must never raise into arbitrary frames
    if callable(_sigusr1_prev):
        # Chain the application's own handler (SIG_DFL/SIG_IGN are ints,
        # not callables) — the dump is additive, never a replacement.
        try:
            _sigusr1_prev(signum, frame)
        except Exception:
            pass


def _safe_dump(fn):
    try:
        fn("SIGUSR1")
    except Exception:
        pass
