"""Chrome-tracing timeline (reference: horovod/common/timeline.{h,cc} —
same phase vocabulary, same per-tensor lanes, same HOROVOD_TIMELINE
activation; device-side spans come from the XLA profiler instead of CUDA
events)."""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

# Activity names (reference: operations.h:29-50).
QUEUE = "QUEUE"
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
# Instant mark inside a NEGOTIATE_* span: process N announced the tensor
# (reference: the per-rank readiness events timeline.cc:106-130 records
# while a tensor is NEGOTIATING — the trace then shows who was late).
RANK_READY = "RANK_READY"

_FLUSH_INTERVAL_S = 1.0  # reference: timeline.h:32


class Timeline:
    """Rank-0 chrome://tracing JSON writer. One "pid" lane per tensor name
    (reference: timeline.cc:60-96 metadata events)."""

    def __init__(self, path: Optional[str]):
        self._path = path
        self._lock = threading.RLock()
        self._fh = None
        self._pids = {}
        self._last_flush = 0.0
        self._first = True
        if path:
            self._fh = open(path, "w")
            self._fh.write("[\n")
            self._start = time.monotonic()
            # Crash-safety: a killed run leaves a truncated file. Events
            # are separator-FIRST (no trailing comma after the last one),
            # which the chrome/Perfetto JSON-array reader accepts without
            # the closing ']'; a clean interpreter exit that never reached
            # close() (engine leaked, Ctrl-C mid-run) is closed here.
            atexit.register(self.close)

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def _ts_us(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _pid(self, name: str) -> int:
        if name not in self._pids:
            pid = len(self._pids) + 1
            self._pids[name] = pid
            self._emit(
                {"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": name}}
            )
        return self._pids[name]

    def _emit(self, ev: dict):
        # Separator BEFORE each event (after the first): however the
        # process dies, the file never ends in a trailing comma, so it
        # stays loadable in Perfetto after truncation.
        sep = "" if self._first else ",\n"
        self._first = False
        self._fh.write(sep + json.dumps(ev))
        now = time.monotonic()
        if now - self._last_flush > _FLUSH_INTERVAL_S:
            self._fh.flush()
            self._last_flush = now

    def now_us(self) -> int:
        """Current timeline clock, for retro-emitted spans (a caller that
        learns a phase boundary only after the fact — e.g. WAIT_FOR_DATA
        split out of an executor round-trip — records explicit ts)."""
        return self._ts_us() if self.enabled else 0

    def _event(self, phase: str, tensor: str, activity: str,
               args: Optional[dict], ts_us: Optional[int] = None):
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:  # closed between the check and the lock
                return
            ev = {"name": activity, "ph": phase, "pid": self._pid(tensor),
                  "ts": self._ts_us() if ts_us is None else ts_us}
            if phase == "i":
                ev["s"] = "p"  # instant scope: process
            if args:
                ev["args"] = args
            self._emit(ev)

    def start(self, tensor: str, activity: str, args: Optional[dict] = None,
              ts_us: Optional[int] = None):
        self._event("B", tensor, activity, args, ts_us)

    def end(self, tensor: str, activity: str, args: Optional[dict] = None,
            ts_us: Optional[int] = None):
        self._event("E", tensor, activity, args, ts_us)

    def instant(self, tensor: str, activity: str,
                args: Optional[dict] = None):
        """Zero-duration mark on the tensor's lane (chrome 'i' event) —
        e.g. RANK_READY instants inside a NEGOTIATE_* span."""
        self._event("i", tensor, activity, args)

    def close(self):
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:  # raced with another closer
                return
            self._fh.write("\n]\n")
            self._fh.close()
            self._fh = None
        # Drop the crash-safety hook: without this, every engine
        # generation's closed Timeline (and its per-tensor lane map)
        # stays pinned by the atexit registry for process lifetime.
        atexit.unregister(self.close)


def timeline_path_from_env() -> Optional[str]:
    """HOROVOD_TIMELINE=<file> activation (reference: operations.cc:1732-1736);
    HVD_TIMELINE is the native spelling."""
    return os.environ.get("HVD_TIMELINE") or os.environ.get("HOROVOD_TIMELINE")


def from_env() -> Timeline:
    return Timeline(timeline_path_from_env())
