"""Async collective engine (reference: the C++ core in horovod/common/ —
HorovodGlobalState + BackgroundThreadLoop, operations.cc:108-247,1604-2172).

The SPMD compute path does not need this engine — collectives compile into
the step. It exists for host-side async callers (the torch frontend's
allreduce_async_/poll/synchronize surface) where framework threads enqueue
tensors and a background dispatcher fuses and executes them.
"""

from horovod_tpu.core.engine import (  # noqa: F401
    Engine,
    EngineError,
    DuplicateNameError,
    get_engine,
    shutdown_engine,
)
