"""Faultline — deterministic fault injection for the control plane.

PR 9 made rank loss survivable, but the only fault the chaos tier could
reproduce was a clean SIGKILL. At pod scale the common failures are
*messy* — slow KV reads, dropped heartbeats, wedged submits, torn
checkpoint writes (arxiv 1909.09756 operates at scales where partial
failure is the steady state; the reference aborts the world on any of
them, arxiv 1802.05799) — and none of the recovery ladder below the
SIGKILL rung is tested unless those faults are injectable on demand.

This module is the injection registry. Sites are named choke points
threaded through the code base; each is one cheap guarded call that is a
no-op (one module-global ``is None`` check) unless ``HVD_FAULTS`` armed
it — no spec means zero overhead and byte-identical behavior (pinned by
tests/test_faultline.py).

Spec grammar (``HVD_FAULTS``, comma-separated)::

    site:mode:count[:param]

- ``site`` — a name from :data:`SITES` below.
- ``mode`` — what to do when the site arms (see the per-site table).
- ``count`` — how many consecutive armings fire: an integer ``N``, ``*``
  (every arming), or ``P%`` (each arming fires with probability P/100,
  drawn from the stream ``HVD_FAULTS_SEED`` seeds — deterministic per
  seed, so a flaky-looking schedule is replayable). An ``@M`` suffix
  (``N@M``, ``*@M``) delays the first firing to the M-th arming
  (1-based) — e.g. ``hb.beat:skip:*@12`` beats healthily 11 times,
  then goes silent forever (the frozen-process signature the lease
  must distinguish from a startup no-show).
- ``param`` — mode-specific (e.g. delay seconds). Everything after the
  third ``:`` is the param, so params may contain colons.

Per-rank scoping is the launcher's job: ``run.py --faults RANK:SPEC``
sets ``HVD_FAULTS`` in that child only (repeatable; several specs for
one rank join with commas).

Sites and modes::

    kv.get       delay(param=s) | error            coordination-KV blocking read
    kv.set       delay(param=s) | error | torn     KV write (torn = half the value lands)
    kv.try_get   delay(param=s) | vanish           KV probe (vanish = key reads absent)
    hb.beat      skip | freeze | vanish            heartbeat publish (skip/freeze stop
                                                   the counter; vanish deletes the key)
    engine.submit  fail                            *_async enqueue raises
    engine.admit   burst(param=N)                  admission pressure: pile N synthetic
                                                   low-priority 1-element submits onto
                                                   the queue ahead of this submit, so
                                                   class budgets saturate on demand
    engine.exec    stall(param=s) | poison | error  executor call (poison = NaN result)
    engine.pool    exhausted                       buffer-pool checkout behaves as if
                                                   the resident cap were reached (fresh
                                                   allocation, counted as a miss)
    ckpt.write     torn                            checkpoint save dies mid-write
    preempt.signal deliver                         behave as if SIGTERM arrived (the
                                                   graceful-preemption ladder fires
                                                   at a deterministic batch)

Every firing increments ``fault.injected`` + ``fault.injected.<site>``,
appends to a bounded record the flight dumps embed (``"faults"`` section
— post-mortems distinguish injected from organic failures), and stamps a
``FAULT_INJECTED`` instant into the live engine's flight-recorder ring
when one exists.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

LOG = logging.getLogger("horovod_tpu.faultline")

#: The valid injection sites (parse errors name this list).
SITES = ("kv.get", "kv.set", "kv.try_get", "hb.beat",
         "engine.submit", "engine.admit", "engine.exec", "engine.pool",
         "ckpt.write", "preempt.signal")

_MODES = {
    "kv.get": ("delay", "error"),
    "kv.set": ("delay", "error", "torn"),
    "kv.try_get": ("delay", "vanish"),
    "hb.beat": ("skip", "freeze", "vanish"),
    "engine.submit": ("fail",),
    "engine.admit": ("burst",),
    "engine.exec": ("stall", "poison", "error"),
    "engine.pool": ("exhausted",),
    "ckpt.write": ("torn",),
    "preempt.signal": ("deliver",),
}


class FaultInjected(RuntimeError):
    """An injected error fault fired. Sites that surface errors through
    an existing exception taxonomy (KVError, EngineError) wrap or
    re-raise it there; the message always carries the ``injected fault``
    marker so post-mortems and tests can tell it from an organic
    failure."""


@dataclass
class _Spec:
    site: str
    mode: str
    remaining: Optional[int]  # None = unlimited ('*' or probabilistic)
    prob: Optional[float]     # None = deterministic count
    param: Optional[str]
    skip_first: int = 0       # armings to pass through before firing
    fired: int = 0

    def describe(self) -> str:
        p = f":{self.param}" if self.param is not None else ""
        n = "*" if self.remaining is None and self.prob is None else (
            f"{self.prob * 100:g}%" if self.prob is not None
            else str(self.remaining))
        at = f"@{self.skip_first + 1}" if self.skip_first else ""
        return f"{self.site}:{self.mode}:{n}{at}{p}"


@dataclass
class Fault:
    """One armed firing, handed back to the call site."""

    site: str
    mode: str
    param: Optional[str]

    def seconds(self, default: float = 0.05) -> float:
        """The param as seconds (delay/stall modes)."""
        try:
            return max(0.0, float(self.param))
        except (TypeError, ValueError):
            return default

    def describe(self) -> str:
        p = f" param={self.param}" if self.param is not None else ""
        return f"injected fault at {self.site}: {self.mode}{p}"


# Armed specs by site. None = disarmed (the zero-overhead fast path: every
# site guard is `if _SPECS is None: return None`). Populated once from
# HVD_FAULTS at import; tests re-arm through configure().
_SPECS: Optional[Dict[str, List[_Spec]]] = None
_RNG = random.Random()
_LOCK = threading.Lock()
# Bounded record of fired faults — embedded in every flight dump.
_RECORDS: List[dict] = []
_RECORD_CAP = 256


class FaultSpecError(ValueError):
    """HVD_FAULTS did not parse. Loud by design: a chaos run with a
    silently-dropped spec would 'pass' without testing anything."""


def _parse(spec: str) -> Dict[str, List[_Spec]]:
    out: Dict[str, List[_Spec]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":", 3)
        if len(fields) < 3:
            raise FaultSpecError(
                f"bad HVD_FAULTS entry {part!r}: want "
                "site:mode:count[:param]")
        site, mode, count = fields[0], fields[1], fields[2]
        param = fields[3] if len(fields) > 3 else None
        if site not in SITES:
            raise FaultSpecError(
                f"unknown fault site {site!r}; valid sites: "
                f"{', '.join(SITES)}")
        if mode not in _MODES[site]:
            raise FaultSpecError(
                f"site {site} has no mode {mode!r}; valid modes: "
                f"{', '.join(_MODES[site])}")
        remaining: Optional[int] = None
        prob: Optional[float] = None
        skip_first = 0
        count, at, offset = count.partition("@")
        if at:
            try:
                skip_first = int(offset) - 1  # '@M' = fire on the M-th
            except ValueError:
                raise FaultSpecError(
                    f"bad '@' offset {offset!r} in {part!r}") from None
            if skip_first < 0:
                raise FaultSpecError(
                    f"'@' offset is 1-based in {part!r}")
        if count == "*":
            pass
        elif count.endswith("%"):
            try:
                prob = float(count[:-1]) / 100.0
            except ValueError:
                raise FaultSpecError(
                    f"bad probability {count!r} in {part!r}") from None
            if not 0.0 <= prob <= 1.0:
                raise FaultSpecError(
                    f"probability {count!r} outside 0-100% in {part!r}")
        else:
            try:
                remaining = int(count)
            except ValueError:
                raise FaultSpecError(
                    f"bad count {count!r} in {part!r}: want an integer, "
                    "'*', or 'P%', each with an optional '@M' "
                    "first-firing offset") from None
            if remaining < 0:
                raise FaultSpecError(f"negative count in {part!r}")
        out.setdefault(site, []).append(
            _Spec(site, mode, remaining, prob, param,
                  skip_first=skip_first))
    return out


def configure(spec: Optional[str], seed: Optional[int] = None):
    """(Re-)arm from a spec string (None/empty disarms). Import-time
    arming reads HVD_FAULTS + HVD_FAULTS_SEED; tests drive this
    directly."""
    global _SPECS, _RNG
    with _LOCK:
        parsed = _parse(spec) if spec else None
        _SPECS = parsed if parsed else None
        _RNG = random.Random(seed)
        _RECORDS.clear()


def reset():
    """Tests only: disarm and clear the fired-fault record."""
    configure(None)


def armed() -> bool:
    return _SPECS is not None


def snapshot() -> List[dict]:
    """Fired-fault records (newest last) — the ``"faults"`` section of
    flight dumps, and the supervisor's injected-vs-organic evidence."""
    with _LOCK:
        return list(_RECORDS)


def active_spec() -> Optional[str]:
    """The armed spec, re-serialized (None when disarmed) — what the
    launcher prints next to a dead child that ran with injections."""
    specs = _SPECS
    if specs is None:
        return None
    return ",".join(s.describe() for group in specs.values()
                    for s in group)


def _stamp_engine_ring(fault: Fault, detail: str):
    """Best-effort FAULT_INJECTED instant into the live engine's
    flight-recorder ring (post-mortems then carry the fault next to the
    rounds it broke). Lazy import — the engine imports this module."""
    try:
        from horovod_tpu.core import engine as _eng

        e = _eng._engine
        if e is None:
            return
        if hasattr(e, "_lib") and getattr(e, "_ptr", None):
            e._lib.hvd_engine_timeline_instant(
                e._ptr, b"fault", b"FAULT_INJECTED",
                (f'"site":"{fault.site}","mode":"{fault.mode}"').encode())
        elif hasattr(e, "timeline"):
            e.timeline.instant("fault", "FAULT_INJECTED",
                               {"site": fault.site, "mode": fault.mode,
                                "detail": detail})
    except Exception:
        pass


def _record(fault: Fault, detail: str):
    try:
        from horovod_tpu.core import telemetry as _tele

        _tele.REGISTRY.counter("fault.injected").inc()
        _tele.REGISTRY.counter(f"fault.injected.{fault.site}").inc()
    except Exception:
        pass
    with _LOCK:
        _RECORDS.append({"site": fault.site, "mode": fault.mode,
                         "param": fault.param, "detail": detail,
                         "wall": round(time.time(), 3)})
        del _RECORDS[:-_RECORD_CAP]
    LOG.warning("FAULT INJECTED %s (%s)", fault.describe(), detail)
    _stamp_engine_ring(fault, detail)


def check(site: str, detail: str = "") -> Optional[Fault]:
    """The site guard: None on the (default) disarmed path, else the
    Fault to act on. Firing is recorded here — call sites only enact the
    mode."""
    specs = _SPECS
    if specs is None:
        return None
    group = specs.get(site)
    if not group:
        return None
    with _LOCK:
        for s in group:
            if s.skip_first > 0:
                s.skip_first -= 1
                continue
            if s.prob is not None:
                if _RNG.random() >= s.prob:
                    continue
            elif s.remaining is not None:
                if s.remaining <= 0:
                    continue
                s.remaining -= 1
            s.fired += 1
            fault = Fault(s.site, s.mode, s.param)
            break
        else:
            return None
    _record(fault, detail)
    return fault


# -- per-site helpers (keep call sites to one line) --------------------------


def kv_get(key: str):
    """kv.get site: may sleep (delay) or raise FaultInjected (error).
    Call INSIDE the KV backend's existing error wrapping so an injected
    error surfaces as a KVError like an organic one."""
    f = check("kv.get", key)
    if f is None:
        return
    if f.mode == "delay":
        time.sleep(f.seconds())
    elif f.mode == "error":
        raise FaultInjected(f.describe() + f" key={key}")


def kv_set(key: str, value: str) -> str:
    """kv.set site: returns the value to actually write (torn = first
    half only); may sleep or raise FaultInjected."""
    f = check("kv.set", key)
    if f is None:
        return value
    if f.mode == "delay":
        time.sleep(f.seconds())
        return value
    if f.mode == "error":
        raise FaultInjected(f.describe() + f" key={key}")
    if f.mode == "torn":
        return value[: len(value) // 2]
    return value


def kv_try_get(key: str) -> bool:
    """kv.try_get site: True = pretend the key is absent (vanish); may
    sleep (delay)."""
    f = check("kv.try_get", key)
    if f is None:
        return False
    if f.mode == "delay":
        time.sleep(f.seconds())
        return False
    return f.mode == "vanish"


def heartbeat() -> Optional[str]:
    """hb.beat site: the mode to apply to this tick's publish
    ('skip' | 'freeze' | 'vanish'), or None."""
    f = check("hb.beat")
    return None if f is None else f.mode


def engine_submit(name: str) -> Optional[str]:
    """engine.submit site: an error message to fail the enqueue with, or
    None (call sites raise their own EngineError so handle/queue
    semantics stay identical to an organic submit failure)."""
    f = check("engine.submit", name)
    if f is None or f.mode != "fail":
        return None
    return f.describe() + f" tensor={name}"


def engine_admit_burst() -> int:
    """engine.admit site: how many synthetic low-priority submits to
    pile onto the queue BEFORE the real submit is admitted (0 = site
    quiet). The engines' single-submit paths call this through
    ``core/engine.py admission_burst_inject`` so class budgets can be
    driven to saturation deterministically."""
    f = check("engine.admit")
    if f is None or f.mode != "burst":
        return 0
    try:
        return max(0, int(f.param))
    except (TypeError, ValueError):
        return 8


def engine_exec(op: str) -> Optional[Fault]:
    """engine.exec site: may sleep in place (stall); returns the Fault
    for 'poison'/'error' so the executor can act on the result."""
    f = check("engine.exec", op)
    if f is None:
        return None
    if f.mode == "stall":
        time.sleep(f.seconds())
        return None
    if f.mode == "error":
        raise FaultInjected(f.describe() + f" op={op}")
    return f  # poison: the executor NaN-fills its result


def pool_exhausted() -> bool:
    """engine.pool site: True = this checkout must behave as if the
    pool's resident cap were reached (fresh allocation, counted as a
    miss, nothing retained) — the degradation rung below OOM that the
    allocation-regression tier exercises on demand."""
    f = check("engine.pool")
    return f is not None and f.mode == "exhausted"


def ckpt_write() -> Optional[Fault]:
    """ckpt.write site: 'torn' — the saver writes half the payload then
    raises, simulating a rank dying mid-save."""
    return check("ckpt.write")


def preempt_signal() -> bool:
    """preempt.signal site: True = behave as if the platform's SIGTERM
    just arrived (core/preempt.py polls this at the trainer's batch
    boundary — armed identically on every rank, a lockstep batch count
    makes the whole graceful-preemption ladder deterministic, which a
    real mid-epoch signal race never is)."""
    f = check("preempt.signal")
    return f is not None and f.mode == "deliver"


# Arm from the environment once at import. A bad spec in a chaos run must
# fail loudly, not silently test nothing.
try:
    _seed = os.environ.get("HVD_FAULTS_SEED")
    configure(os.environ.get("HVD_FAULTS"),
              int(_seed) if _seed else None)
    if armed():
        LOG.warning("fault injection ARMED: %s (HVD_FAULTS)",
                    active_spec())
except FaultSpecError:
    raise
except ValueError as exc:  # bad HVD_FAULTS_SEED
    raise FaultSpecError(f"bad HVD_FAULTS_SEED: {exc}") from None
