"""Elastic worlds — survive rank loss, shrink the mesh, regrow on rejoin.

The reference dies with its first lost peer: MPI is the only control
plane, and a SIGKILLed rank aborts the world. This module (beyond-
reference scope — no PARITY row maps to it; see ROADMAP open item 3)
turns a rank loss into a *reconfiguration*:

1. **Detection — missed-heartbeat KV lease.** Every process beats a
   sequence counter into the coordination-service KV store
   (``hvd/elastic/g<gen>/hb/p<pid>``). A peer whose counter stops
   advancing for ``HVD_ELASTIC_LEASE_S`` (observed on the *reader's*
   clock — no cross-host clock comparison) hardens into a death
   verdict; ``NegotiationTimeout``/silent negotiation waits consult the
   same lease through :func:`coordinator.set_liveness_probe`, so a
   blocked engine round fails over in seconds instead of waiting out
   ``HVD_NEGOTIATION_TIMEOUT``. Survivors write a tombstone, dump the
   flight recorder with the attribution, and flag the world as changed.

2. **Shrink — in-process reconfiguration.** When the survivors of a
   death verdict are exactly this process's local chips, the world is
   rebuilt in place: the engine is drained (aborting in-flight
   negotiation; the response cache dies with its coordinator and the
   next incarnation starts at a fresh epoch), the poisoned runtime
   backend is *leaked* (its execution chain holds errors from
   collectives the dead peer never joined — destroying it would join
   threads blocked in dead sockets) and a fresh single-process backend
   is built, the 1-D ``'hvd'`` mesh is re-made over the surviving chips
   with re-densified ranks, and the trainer resumes from the newest
   checkpoint through the existing host-first ``broadcast_state``
   pattern — a recompile, not a crash. Multi-controller survivor sets
   (and worlds that would drop below ``HVD_ELASTIC_MIN_NP``) take the
   coordinated-restart path instead: exit with
   :data:`RESTART_EXIT_CODE` and let the supervisor relaunch the full
   world from the newest checkpoint (``run.py --elastic``).

3. **Regrow — blacklist-then-readmit.** The supervisor restarts dead
   children with capped backoff; a recovered rank is blacklisted for
   ``HVD_ELASTIC_BLACKLIST_S`` (flap protection) before the supervisor
   files a rejoin request. Survivors see the request at an epoch
   boundary, checkpoint, and exit for restart; the supervisor relaunches
   the full world at the next **world epoch**, which resumes from the
   newest checkpoint and verifies agreement with
   ``hvd.check_consistency``.

Every transition is observable: ``world.epoch`` / ``world.size`` /
``world.processes`` / ``world.degraded`` gauges, a ``RECONFIGURE``
span in the flight dump written per epoch change, and ``/healthz``
reporting the degraded world (core/sentinel.py).

State shared with the supervisor (join requests, restart votes, the
epoch journal) lives as files under ``HVD_ELASTIC_DIR`` — it must
survive the coordination service, whose host may itself be the casualty.
In-world state (heartbeats, tombstones) rides the existing KV store.

``HVD_ELASTIC`` unset/0 keeps today's fail-fast semantics bit-for-bit:
nothing here activates, the launcher kills the world on first death, and
``NegotiationTimeout`` raises untouched.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.core import telemetry as _tele
from horovod_tpu.core import timeline as tl
from horovod_tpu.core.sentinel import _env_float

LOG = logging.getLogger("horovod_tpu.elastic")

#: Exit code a member uses to vote for a coordinated full-world restart
#: (regrow at an epoch boundary, multi-survivor shrink, below-min-np).
#: The supervisor (run.py --elastic) relaunches the whole world when it
#: sees it; anything else keeps ordinary meaning.
RESTART_EXIT_CODE = 77


def enabled() -> bool:
    """HVD_ELASTIC=1 opts the process into elastic-world semantics."""
    return os.environ.get("HVD_ELASTIC", "0").lower() not in (
        "0", "", "false", "off")


def lease_s() -> float:
    """Missed-heartbeat lease: a peer silent this long is dead."""
    return _env_float("HVD_ELASTIC_LEASE_S", 3.0)


def grace_s() -> float:
    """Startup grace before a *never-heard-from* peer can be declared
    dead (covers launch/import skew across the cohort)."""
    return _env_float("HVD_ELASTIC_GRACE_S", 30.0)


def blacklist_s() -> float:
    """Readmission backoff for a recovered host (flap protection) — the
    supervisor waits this long after a death before filing the rejoin
    request; doubled per repeat death of the same rank."""
    return _env_float("HVD_ELASTIC_BLACKLIST_S", 5.0)


def min_np() -> int:
    """Smallest process count the world may shrink to in place
    (``run.py --elastic --min-np K`` exports it). Below it, survivors
    vote for a full-world restart instead of training degraded."""
    try:
        return max(1, int(os.environ.get("HVD_ELASTIC_MIN_NP", "1")))
    except ValueError:
        return 1


def generation() -> int:
    """Supervisor relaunch counter (0 for the first world)."""
    try:
        return int(os.environ.get("HVD_ELASTIC_GENERATION", "0"))
    except ValueError:
        return 0


def elastic_dir() -> Optional[str]:
    return os.environ.get("HVD_ELASTIC_DIR") or None


def checkpoint_dir() -> Optional[str]:
    """Where elastic training checkpoints live: HVD_CHECKPOINT_DIR, or
    ``<HVD_ELASTIC_DIR>/ckpt`` when a supervisor runs the world."""
    explicit = os.environ.get("HVD_CHECKPOINT_DIR")
    if explicit:
        return explicit
    d = elastic_dir()
    return os.path.join(d, "ckpt") if d else None


class WorldChanged(Exception):
    """A death verdict landed: the current mesh is gone; reconfigure."""


class ElasticRestartRequired(Exception):
    """This transition needs a supervisor-coordinated full-world restart
    (multi-survivor shrink, below-min-np world, rejoin admission)."""


def _write_json_atomic(path: str, payload: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def bring_up_distributed(coordinator_address: str, num_processes: int,
                         process_id: int):
    """Elastic-mode jax.distributed bring-up.

    The stock ``jax.distributed.initialize`` arms the coordination
    service's own failure detector: ~100 s after a peer stops
    heartbeating, the service propagates a fatal error and every
    surviving client **terminates the process** (LOG(QFATAL) in
    xla/pjrt/distributed/client.h) — the exact opposite of surviving.
    Elastic worlds therefore own the bring-up: the service is created
    with an effectively infinite missed-heartbeat budget (death
    detection is THIS module's KV lease, not the service's), and the
    client skips the shutdown barrier at destruction (it can never pass
    with a dead member). The populated ``global_state`` is the same one
    the rest of jax reads, so everything downstream is unchanged."""
    import jax  # noqa: F401  (backend flags must be settable later)
    from jax._src import distributed as _dist
    from jax._src.lib import xla_extension as _xe

    gs = _dist.global_state
    if gs.client is not None:
        return
    bind = "[::]:" + coordinator_address.rsplit(":", 1)[1]
    if process_id == 0 and gs.service is None:
        gs.service = _xe.get_distributed_runtime_service(
            bind, num_processes,
            heartbeat_interval=10, max_missing_heartbeats=1_000_000)
    gs.client = _xe.get_distributed_runtime_client(
        coordinator_address, process_id,
        init_timeout=int(_env_float("HVD_ELASTIC_INIT_TIMEOUT", 120.0)),
        shutdown_on_destruction=False)
    gs.client.connect()
    gs.process_id = process_id
    gs.num_processes = num_processes
    gs.coordinator_address = coordinator_address
    LOG.info("elastic distributed world up: %d process(es), this is %d",
             num_processes, process_id)


class ElasticWorld:
    """Per-process elastic state machine (singleton via
    :func:`get_world`). Inert until :meth:`on_init` sees a live
    topology with elastic enabled."""

    def __init__(self):
        self.active = False
        self.epoch = 0
        self.pid = 0             # process index in the CURRENT world
        self.nproc = 1
        self.initial_np = 1
        self.live: List[int] = []
        self.dead: Dict[int, str] = {}
        self.generation = generation()
        self._changed = threading.Event()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._kv = None
        self._seq = 0
        self._started_at = time.monotonic()
        # peer -> (last value seen, monotonic time it last CHANGED):
        # liveness is judged by the counter advancing on OUR clock, so
        # cross-host wall-clock skew can never fake a death.
        self._beats: Dict[int, tuple] = {}
        # Peers with a standing announce_done mark (no verdicts for
        # them until they announce_active again).
        self._done_peers: set = set()
        # Backend objects deliberately kept alive forever after a
        # shrink: destroying a runtime whose execution chain still holds
        # threads blocked in a dead peer's sockets is undefined.
        self._leaked: list = []

    # -- lifecycle -----------------------------------------------------------

    def on_init(self, num_processes: int, process_index: int):
        """Called from ``topology.init`` once the world is known."""
        if not enabled():
            return
        self.active = True
        self.pid = process_index
        self.nproc = num_processes
        if not self.live:
            self.initial_np = num_processes
            self.live = list(range(num_processes))
        self.generation = generation()
        self._load_journal()
        self._publish_gauges()
        from horovod_tpu.core import coordinator as _coord

        _coord.set_world_epoch(self.epoch)
        _coord.set_liveness_probe(self.peer_is_dead)
        if num_processes > 1 and (self._thread is None
                                  or not self._thread.is_alive()):
            # is_alive check: the loop self-terminates when a shrink
            # drops the world to one controller — a later re-entry into
            # a multi-process world must get a FRESH lease thread, not
            # a dead handle.
            self._started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._beat_loop, name="hvd-elastic-heartbeat",
                daemon=True)
            self._thread.start()
        if self.pid == 0 and elastic_dir() and self.epoch == 0 \
                and self.generation == 0:
            self._write_journal("init")

    def _load_journal(self):
        """Adopt the epoch journal (monotonic across supervisor
        generations): a relaunched generation continues the epoch
        sequence instead of restarting it at 0."""
        d = elastic_dir()
        if not d:
            return
        try:
            with open(os.path.join(d, "epoch.json")) as fh:
                rec = json.load(fh)
            prev = int(rec.get("epoch", 0))
        except (OSError, ValueError):
            return
        if self.generation > int(rec.get("generation", 0)) \
                or rec.get("restart_pending"):
            # This is the relaunched world after a coordinated restart:
            # the regrow/restart transition is the epoch bump.
            self.epoch = prev + 1
            if self.pid == 0:
                self._write_journal("regrow")
        else:
            self.epoch = max(self.epoch, prev)

    def _write_journal(self, kind: str, **extra):
        d = elastic_dir()
        if not d:
            return
        try:
            os.makedirs(d, exist_ok=True)
            _write_json_atomic(os.path.join(d, "epoch.json"), {
                "epoch": self.epoch, "kind": kind, "np": self.nproc,
                "generation": self.generation,
                "dead": sorted(self.dead),
                "wall": round(time.time(), 3), **extra})
        except OSError as exc:
            LOG.warning("cannot write elastic epoch journal: %s", exc)

    def _publish_gauges(self):
        """world.* gauges — /healthz, utils/stats and telemetry_report
        all read these."""
        try:
            from horovod_tpu.common import topology as topo

            size = topo.size() if topo.is_initialized() else 0
        except Exception:
            size = 0
        _tele.REGISTRY.gauge("world.epoch").set(self.epoch)
        _tele.REGISTRY.gauge("world.size").set(size)
        _tele.REGISTRY.gauge("world.processes").set(self.nproc)
        _tele.REGISTRY.gauge("world.initial_processes").set(self.initial_np)
        _tele.REGISTRY.gauge("world.degraded").set(
            1 if self.nproc < self.initial_np else 0)

    # -- heartbeat lease ------------------------------------------------------

    def _ns(self) -> str:
        return f"hvd/elastic/g{self.generation}"

    def _hb_key(self, p: int) -> str:
        return f"{self._ns()}/hb/p{p}"

    def _done_key(self, p: int) -> str:
        return f"{self._ns()}/done/p{p}"

    def _tomb_key(self, p: int) -> str:
        return f"{self._ns()}/dead/p{p}"

    def _get_kv(self):
        if self._kv is None:
            from horovod_tpu.core import coordinator as _coord

            self._kv = _coord.JaxKV()
        return self._kv

    def _beat_loop(self):
        interval = max(0.1, lease_s() / 4.0)
        while not self._stop.wait(interval):
            if not self._beat_once():
                return

    def _beat_once(self) -> bool:
        """One heartbeat tick: publish our counter, judge each peer's.
        Returns False when the loop should stop (lone controller)."""
        with self._lock:
            if self.nproc <= 1:
                return False  # shrunk to a lone controller: no lease
            peers = [p for p in self.live
                     if p != self.pid and p not in self.dead]
        try:
            kv = self._get_kv()
        except Exception:
            return True  # coordination service not up yet
        self._seq += 1
        try:
            # The coordination-service KV is INSERT-ONLY (a second set
            # of the same key fails ALREADY_EXISTS): each beat deletes
            # then re-inserts. A reader landing in the gap sees a
            # missing key for one tick, which deliberately does NOT
            # advance any verdict below.
            kv.delete(self._hb_key(self.pid))
            kv.set(self._hb_key(self.pid), str(self._seq))
        except Exception:
            return True  # KV down: rank 0 died — supervisor territory
        now = time.monotonic()
        for p in peers:
            try:
                val = kv.try_get(self._hb_key(p))
                tomb = kv.try_get(self._tomb_key(p))
                done = kv.try_get(self._done_key(p))
            except Exception:
                break
            if done is not None:
                # The peer ANNOUNCED completion (announce_done) before
                # going silent: that is a finished rank, not a casualty
                # — no verdict while the mark stands. (Without this,
                # the first rank to finish a job would be "dead" to any
                # slower peer.) The mark is revocable: announce_active
                # (a later fit) deletes the key and normal leasing
                # resumes, so the beat clock keeps updating below.
                if p not in self._done_peers:
                    self._done_peers.add(p)
                    LOG.info("elastic: process %d announced completion",
                             p)
                if val is not None:
                    last = self._beats.get(p)
                    if last is None or last[0] != val:
                        self._beats[p] = (val, now)
                continue
            if p in self._done_peers:
                # Mark revoked (announce_active): grant a fresh lease —
                # the clock may have run out while the mark stood, and
                # an instant verdict on revocation would punish a peer
                # for having finished politely.
                self._done_peers.discard(p)
                if val is not None:
                    self._beats[p] = (val, now)
            if tomb is not None:
                self._declare_dead(p, "peer tombstone: " + str(tomb)[:200])
                continue
            if val is None:
                # Never-heard-from peer past the startup grace is dead.
                # A peer we HAVE seen is usually just mid delete->set
                # gap — but a key missing for a whole lease means the
                # peer died INSIDE its gap and will never re-insert.
                last = self._beats.get(p)
                if last is None:
                    if now - self._started_at > grace_s():
                        self._declare_dead(
                            p, f"no heartbeat within the "
                               f"{grace_s():.0f}s startup grace")
                elif now - last[1] > lease_s():
                    self._declare_dead(
                        p, f"heartbeat key vanished and stayed gone "
                           f"({now - last[1]:.1f}s > "
                           f"{lease_s():.1f}s lease)")
                continue
            last = self._beats.get(p)
            if last is None or last[0] != val:
                self._beats[p] = (val, now)
            elif now - last[1] > lease_s():
                self._declare_dead(
                    p, f"heartbeat lease expired "
                       f"({now - last[1]:.1f}s > "
                       f"{lease_s():.1f}s without a beat)")
        return True

    def _declare_dead(self, p: int, reason: str):
        with self._lock:
            if p in self.dead:
                return
            self.dead[p] = reason
        LOG.error("elastic death verdict: process %d is dead (%s); "
                  "world epoch %d will reconfigure", p, reason, self.epoch)
        _tele.REGISTRY.counter("world.deaths").inc()
        try:
            self._get_kv().set(self._tomb_key(p),
                               json.dumps({"by": self.pid,
                                           "reason": reason}))
        except Exception:
            pass
        d = elastic_dir()
        if d:
            try:
                os.makedirs(os.path.join(d, "death"), exist_ok=True)
                _write_json_atomic(
                    os.path.join(d, "death", f"p{p}.json"),
                    {"process": p, "reason": reason, "by": self.pid,
                     "generation": self.generation, "epoch": self.epoch,
                     "wall": round(time.time(), 3)})
            except OSError:
                pass
        # The attributed post-mortem, while the engine ring still holds
        # the rounds that stalled on the dead peer.
        self._dump(f"death verdict: process {p} ({reason}); "
                   f"world epoch {self.epoch} reconfiguring")
        self._changed.set()

    def _dump(self, reason: str):
        try:
            fdir = os.environ.get("HVD_FLIGHT_DIR")
            if fdir:
                os.makedirs(fdir, exist_ok=True)
            events = []
            from horovod_tpu.core import engine as _eng

            e = _eng._engine
            if e is not None:
                if hasattr(e, "recent_events"):
                    events = list(e.recent_events())
                else:
                    events = list(e.timeline.recent())
            last_ts = events[-1].get("ts") if events else 0
            base = int(last_ts) if isinstance(last_ts, (int, float)) else 0
            # The RECONFIGURE span: trace-merge-compatible events framing
            # the transition next to the rounds that led to it.
            events.append({"name": "RECONFIGURE", "ph": "B",
                           "ts": base + 1, "args": {"reason": reason,
                                                    "epoch": self.epoch}})
            events.append({"name": "RECONFIGURE", "ph": "E",
                           "ts": base + 2})
            tl.dump_and_warn(events, reason, tl._process_index(), LOG)
        except Exception:
            LOG.warning("elastic flight dump failed", exc_info=True)

    # -- verdict surface ------------------------------------------------------

    def peer_is_dead(self, p: int) -> Optional[str]:
        """Liveness probe (also wired into coordinator._read_peer): the
        death reason when process ``p`` has a verdict, else None."""
        with self._lock:
            return self.dead.get(p)

    def world_changed(self) -> bool:
        return self._changed.is_set()

    def dead_peers(self) -> Dict[int, str]:
        with self._lock:
            return dict(self.dead)

    def await_verdict(self, timeout_s: float) -> bool:
        """Wait briefly for a death verdict — used when a step raised and
        the caller needs to know whether a dying peer explains it."""
        return self._changed.wait(timeout_s)

    # -- reconfiguration ------------------------------------------------------

    def reconfigure(self):
        """Act on the death verdict: shrink the world in place when the
        survivors are exactly this controller's chips, else raise
        :class:`ElasticRestartRequired` for the supervisor path. Returns
        the new world epoch."""
        with self._lock:
            dead = dict(self.dead)
            survivors = sorted(p for p in self.live if p not in dead)
        if not dead:
            return self.epoch
        if len(survivors) < min_np():
            raise ElasticRestartRequired(
                f"{len(survivors)} survivor(s) < --min-np {min_np()}; "
                "waiting for the supervisor to regrow the world")
        if survivors != [self.pid]:
            raise ElasticRestartRequired(
                f"survivors {survivors} span multiple controllers; "
                "in-place shrink needs a coordinated restart")
        t0 = time.monotonic()
        old_epoch, old_np = self.epoch, self.nproc
        self._mark_reconfigure_on_timeline()
        from horovod_tpu.common import topology as topo

        LOG.warning("elastic shrink: draining the engine and tearing "
                    "down world epoch %d", old_epoch)
        topo.shutdown()  # drains the engine; aborts in-flight negotiation
        LOG.warning("elastic shrink: old world down; rebuilding a "
                    "single-controller backend over the local chips")
        devs = self._rebuild_local_backend()
        topo.init(devices=devs)
        with self._lock:
            self.epoch = old_epoch + 1
            self.nproc = 1
            self.pid = 0  # ranks re-densified: the lone controller is 0
            self.live = [0]
            self._changed.clear()
            self.dead = {}
            dead_list = sorted(dead)
        from horovod_tpu.core import coordinator as _coord

        _coord.set_world_epoch(self.epoch)
        self._write_journal("shrink", lost=dead_list)
        self._publish_gauges()
        _tele.REGISTRY.counter("world.reconfigures").inc()
        reason = (f"RECONFIGURE: world epoch {old_epoch} -> {self.epoch}; "
                  f"lost process(es) {dead_list} "
                  f"({'; '.join(dead[p] for p in dead_list)}); "
                  f"continuing with 1/{old_np} controller(s), "
                  f"{len(devs)} rank(s), after "
                  f"{time.monotonic() - t0:.1f}s")
        LOG.warning(reason)
        self._dump(reason)
        return self.epoch

    def _mark_reconfigure_on_timeline(self):
        """Best-effort RECONFIGURE instant on the live engine timeline
        before it is torn down — per-rank traces then carry the
        transition, not just the flight dumps."""
        try:
            from horovod_tpu.core import engine as _eng

            e = _eng._engine
            if e is None:
                return
            if hasattr(e, "_lib") and getattr(e, "_ptr", None):
                e._lib.hvd_engine_timeline_instant(
                    e._ptr, b"world", b"RECONFIGURE",
                    f'"epoch":{self.epoch}'.encode())
            elif hasattr(e, "timeline"):
                e.timeline.instant("world", "RECONFIGURE",
                                   {"epoch": self.epoch})
        except Exception:
            pass

    def _rebuild_local_backend(self):
        """Swap in a fresh single-process runtime.

        The old backend's collective-execution chain is poisoned: the
        program in flight when the peer died eventually fails with a
        socket error, and every execution enqueued after it inherits the
        error forever. The old client (and the arrays living on it) is
        LEAKED — its destructor would join threads still blocked inside
        the dead peer's sockets — and a new backend is created with the
        distributed client detached, so it comes up single-process with
        in-process collectives only."""
        import jax
        from jax._src import distributed as _dist

        gs = _dist.global_state
        try:
            self._leaked.append(jax.local_devices()[0].client)
        except Exception:
            pass
        kv_client = gs.client
        self._leaked.append(kv_client)
        gs.client = None
        gs.num_processes = 1
        gs.process_id = 0
        try:
            if jax.default_backend() == "cpu":
                # The fresh CPU client must not re-wire gloo over the
                # dead world's store.
                jax.config.update(
                    "jax_cpu_collectives_implementation", "none")
        except Exception:
            pass
        try:
            jax.clear_backends()
        except AttributeError:  # removed from the jax namespace in 0.4.36
            from jax._src import api as _api

            _api.clear_backends()
        jax.clear_caches()
        # (topology.shutdown — already run by reconfigure — cleared the
        # mesh-keyed program and zero-tree caches.)
        devs = jax.devices()
        # The KV plane stays reachable (tombstone reads, debugging);
        # jax's own world-size view remains 1.
        gs.client = kv_client
        return devs

    # -- supervisor protocol (files under HVD_ELASTIC_DIR) -------------------

    def restart_requested(self) -> Optional[str]:
        """A pending coordinated-restart request (rejoin admission filed
        by the supervisor, or a member's restart vote), or None."""
        d = elastic_dir()
        if not d:
            return None
        try:
            rejoin = os.path.join(d, "rejoin")
            if os.path.isdir(rejoin):
                reqs = [f for f in os.listdir(rejoin)
                        if f.endswith(".json")]
                if reqs:
                    return f"rejoin request(s) pending: {sorted(reqs)}"
            if os.path.exists(os.path.join(d, "restart.json")):
                with open(os.path.join(d, "restart.json")) as fh:
                    return json.load(fh).get("reason", "restart requested")
        except (OSError, ValueError):
            return None
        return None

    def request_restart(self, reason: str):
        d = elastic_dir()
        if not d:
            return
        try:
            os.makedirs(d, exist_ok=True)
            _write_json_atomic(os.path.join(d, "restart.json"),
                               {"reason": reason, "by": self.pid,
                                "generation": self.generation,
                                "wall": round(time.time(), 3)})
        except OSError as exc:
            LOG.warning("cannot file restart request: %s", exc)

    def exit_for_restart(self, reason: str):
        """Leave the process with the supervisor's restart exit code.
        ``os._exit``: interpreter teardown would hang in the distributed
        client/backend destructors of a world with dead members."""
        # A restart voter going silent must read as a PLANNED exit, not
        # a casualty: without the done mark, peers still mid-epoch
        # lease-verdict this rank and shrink pointlessly before
        # honoring the same restart request themselves.
        self.announce_done()
        self._write_journal("restart_pending", restart_pending=True,
                            reason=reason)
        LOG.warning("elastic coordinated restart: %s (exiting with "
                    "code %d for the supervisor)", reason,
                    RESTART_EXIT_CODE)
        self._dump(f"RECONFIGURE: coordinated restart ({reason})")
        try:
            import sys

            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        os._exit(RESTART_EXIT_CODE)

    def park(self, obj):
        """Keep ``obj`` alive for the rest of the process (the public
        face of the leak list): state donated into a wedged old-world
        execution must never run its destructor — it can block inside
        the dead runtime."""
        self._leaked.append(obj)

    def announce_done(self):
        """Tell the cohort this process finished its training work
        CLEANLY (``Trainer.fit`` calls it at train end; custom loops
        should too, before their final barriers, while the whole cohort
        is still up): silent-after-done peers get no death verdict —
        the last ranks of a finishing job must not shrink the world out
        from under each other. Revoked by :meth:`announce_active`."""
        if not self.active or self.nproc <= 1:
            return
        try:
            kv = self._get_kv()
            kv.delete(self._done_key(self.pid))  # insert-only store
            kv.set(self._done_key(self.pid), str(round(time.time(), 3)))
        except Exception:
            pass

    def announce_active(self):
        """Revoke a standing completion mark (a later ``fit`` on the
        same world): peers resume leasing this process normally."""
        if not self.active or self.nproc <= 1:
            return
        try:
            self._get_kv().delete(self._done_key(self.pid))
        except Exception:
            pass

    def shutdown(self):
        self._stop.set()

    # -- reporting ------------------------------------------------------------

    def summary(self) -> Optional[dict]:
        if not self.active:
            return None
        try:
            from horovod_tpu.common import topology as topo

            size = topo.size() if topo.is_initialized() else 0
        except Exception:
            size = 0
        with self._lock:
            return {"epoch": self.epoch, "generation": self.generation,
                    "size": size, "processes": self.nproc,
                    "initial_processes": self.initial_np,
                    "degraded": self.nproc < self.initial_np,
                    "dead": dict(self.dead)}


_world: Optional[ElasticWorld] = None
_world_lock = threading.Lock()


def get_world() -> ElasticWorld:
    global _world
    with _world_lock:
        if _world is None:
            _world = ElasticWorld()
        return _world


def reset_world():
    """Tests only: drop the singleton so a fresh env is re-read."""
    global _world
    with _world_lock:
        if _world is not None:
            _world.shutdown()
        _world = None


def active() -> bool:
    return enabled() and get_world().active


def world_summary() -> Optional[dict]:
    """The /healthz ``world`` section (None when elastic is off)."""
    if not enabled() or _world is None:
        return None
    return _world.summary()


def maybe_restore(trainer, x_sample) -> int:
    """Resume a Trainer from the newest elastic checkpoint; returns the
    epoch to resume AT (0 when there is nothing to restore). The restore
    broadcasts from root — the host-first pattern — so every member of a
    regrown world starts bitwise-identical."""
    from horovod_tpu.utils import checkpoint as _ckpt

    d = checkpoint_dir()
    if not d:
        return 0
    path = _ckpt.latest_checkpoint(d)
    if not path:
        return 0
    trainer.load(path, x_sample)
    trainer.broadcast_state()
    LOG.info("elastic resume: restored %s (epoch %d)", path,
             trainer._epoch)
    return int(trainer._epoch) + 1
